"""Persistent radix prefix cache: cross-call KV reuse in the paged engine.

Fast tier (CPU, XLA paged-attention path, deliberately tiny model): the
acceptance properties the chip never needs to prove —

- warm repeats prefill >= 70% fewer prompt tokens than the cold pass and
  stay BIT-IDENTICAL (greedy and seeded sampling, cache on vs off);
- a fused multi-task batch (four different few-shot templates, global
  LCP ~ 0) shares >= 1 page per task group;
- single-prompt serve-mode requests hit the cache across calls and HTTP
  submissions;
- LRU eviction under a deliberately tiny pool keeps decode admitted and
  outputs exact; preemption of a rider whose prefix is cached resumes
  correctly; dp and tp engines agree with the unsharded one.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from reval_tpu.inference.tpu.engine import EngineStats
from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
from reval_tpu.inference.tpu.prefix_cache import RadixPrefixCache
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params
from reval_tpu.runtime import PagedRuntime

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PAGE = 16      # small pages: multi-page prefixes from short prompts = fast


@pytest.fixture(autouse=True)
def _xla_paged_backend(monkeypatch):
    """Pin the portable XLA paged-attention path: the persisted autotune
    decision may select a TPU Pallas kernel this CPU host cannot build."""
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "xla")


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=2, num_kv_heads=1, head_dim=16)
    params = init_random_params(cfg, seed=0, dtype="float32")
    return cfg, params


def make_engine(tiny, *, prefix_sharing=True, slots=2, max_seq_len=512,
                num_pages=None, seed=0):
    cfg, params = tiny
    return PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=slots,
                          page_size=PAGE, max_seq_len=max_seq_len,
                          num_pages=num_pages, seed=seed,
                          prefix_sharing=prefix_sharing)


TEMPLATE = "def helper(a, b):\n    return a * b + a - b\n\n" * 3
PROMPTS = [TEMPLATE + t for t in ["x = 1", "y = 2", "z = 3"]]

# four task-like groups: distinct few-shot templates, shared within a
# group only — the fused fleet batch shape whose GLOBAL LCP is ~ 0
# (every ByteTokenizer prompt starts with BOS, so the true LCP is 1
# token: under one page, i.e. zero shareable pages)
TASK_TEMPLATES = [
    "# coverage\n" + "line = %d\n" % 7 * 12,
    "! path\n" + "step -> next\n" * 12,
    "@ state\n" + "x: int = 99\n" * 12,
    "~ output\n" + "print(42)\n" * 14,
]
FUSED = [t + f"tail_{i}" for t in TASK_TEMPLATES for i in range(3)]


# ---------------------------------------------------------------------------
# cache data structure (no model)
# ---------------------------------------------------------------------------

class TestRadixCacheUnit:
    def _mk(self, num_pages=32, watermark=2):
        rt = PagedRuntime(num_pages=num_pages, page_size=PAGE, max_slots=2,
                          max_pages_per_seq=16)
        st = EngineStats()
        return rt, st, RadixPrefixCache(rt, PAGE, watermark=watermark,
                                        stats=lambda: st)

    def test_insert_match_extend(self):
        rt, st, c = self._mk()
        ids_a = list(range(2 * PAGE + 5))
        node_a, new_from = c.acquire(ids_a)
        assert new_from == 0 and node_a.tok_len == 2 * PAGE
        assert c.cached_pages == 2 and st.prefix_inserted_pages == 2
        # exact repeat: full hit, nothing new
        node_a2, nf = c.acquire(ids_a)
        assert node_a2 is node_a and nf == 2 * PAGE
        assert st.prefix_hit_tokens == 2 * PAGE
        # longer prompt extends the chain, sharing the first two pages
        ids_b = list(range(2 * PAGE)) + [99] * (PAGE + 3)
        node_b, nfb = c.acquire(ids_b)
        assert node_b.parent is node_a and nfb == 2 * PAGE
        assert node_b.tok_len == 3 * PAGE and c.cached_pages == 3
        assert c.match_len(ids_b) == 3 * PAGE
        # the chain's pages really are SHARED in the pool (refcounted),
        # not copied: 3 distinct pages live
        assert rt.free_pages == rt.num_pages - 1 - 3
        rt.close()

    def test_pin_blocks_eviction_lru_order(self):
        rt, st, c = self._mk()
        node_a, _ = c.acquire([1] * (PAGE + 1))
        node_b, _ = c.acquire([2] * (PAGE + 1))
        assert c.evict_lru(10) == 0          # both pinned
        c.unpin(node_a)
        assert c.evict_lru(1) == 1 and st.prefix_evictions == 1
        assert c.match_len([1] * (PAGE + 1)) == 0      # a evicted
        assert c.match_len([2] * (PAGE + 1)) == PAGE   # b survives (pinned)
        c.unpin(node_b)
        # LRU: touch b by re-acquiring, then add c; evicting one must
        # pick the stalest (c after b's touch? no — c is fresher; a new
        # distinct node d then b stays fresher than d? d is newest).
        node_c, _ = c.acquire([3] * (PAGE + 1))
        c.unpin(node_c)
        node_b2, _ = c.acquire([2] * (PAGE + 1))       # freshen b
        c.unpin(node_b2)
        assert c.evict_lru(1) == 1
        assert c.match_len([3] * (PAGE + 1)) == 0      # c was LRU
        assert c.match_len([2] * (PAGE + 1)) == PAGE
        rt.close()

    def test_watermark_caps_insertion(self):
        # 8 usable pages, watermark 4: at most 4 pages may be cached
        rt, st, c = self._mk(num_pages=9, watermark=4)
        node, _ = c.acquire(list(range(6 * PAGE + 1)))
        assert c.cached_pages == 4 and node.tok_len == 4 * PAGE
        assert rt.free_pages == 4
        # a second distinct prompt can only evict unpinned pages; node is
        # pinned so nothing moves
        node2, _ = c.acquire([7] * (3 * PAGE))
        assert node2 is None and c.cached_pages == 4
        c.unpin(node)
        # now eviction makes room page by page
        node3, _ = c.acquire([7] * (3 * PAGE))
        assert node3 is not None and c.cached_pages <= 4
        rt.close()

    def test_drop_tail_rolls_back_failed_insert(self):
        """A failed node prefill must remove exactly the new chain —
        uncommitted KV may never survive to serve a later rider."""
        rt, _, c = self._mk()
        base, _ = c.acquire(list(range(2 * PAGE + 1)))      # 2 cached pages
        c.unpin(base)
        ids = list(range(2 * PAGE)) + [77] * (2 * PAGE + 1)
        node, new_from = c.acquire(ids)
        assert new_from == 2 * PAGE and node.tok_len == 4 * PAGE
        free_before = rt.free_pages
        c.drop_tail(node, new_from)                          # rollback
        assert c.match_len(ids) == 2 * PAGE                  # base survives
        assert c.cached_pages == 2
        assert rt.free_pages == free_before + 2              # tail freed
        rt.close()

    def test_clear_returns_all_pages(self):
        rt, _, c = self._mk()
        n, _ = c.acquire(list(range(4 * PAGE)))
        c.unpin(n)
        c.clear()
        assert rt.free_pages == rt.num_pages - 1 and c.cached_pages == 0
        rt.close()


# ---------------------------------------------------------------------------
# engine: warm repeats, bit identity, multi-prefix batches
# ---------------------------------------------------------------------------

def total_tokens(prompts):
    tok = ByteTokenizer()
    return sum(len(tok.encode(p)) for p in prompts)


def test_warm_repeat_prefills_70pct_fewer_bit_identical(tiny):
    """The fleet-repeat shape: repeat 2 of the SAME fused multi-template
    batch must reuse every template's cached pages — >= 70% fewer prompt
    tokens prefilled (the acceptance bar) and bit-identical output."""
    off = make_engine(tiny, prefix_sharing=False)
    want = off.generate(FUSED, max_new_tokens=6, temperature=0.0)
    off.close()

    eng = make_engine(tiny)
    got_cold = eng.generate(FUSED, max_new_tokens=6, temperature=0.0)
    cold = eng.stats.prefill_tokens
    got_warm = eng.generate(FUSED, max_new_tokens=6, temperature=0.0)
    warm = eng.stats.prefill_tokens - cold
    assert got_cold == want and got_warm == want
    assert warm <= 0.3 * cold, (warm, cold)
    assert eng.stats.prefix_hit_tokens > 0
    # the cold pass itself beats no-sharing: in-batch riders hit template
    # pages inserted by their group's first prompt
    assert cold < total_tokens(FUSED)
    eng.close()


def test_cache_on_off_bit_identity_seeded_sampling(tiny):
    """Sampling streams are schedule-independent (fold_in(key, pos)), so
    cache on/off must agree TOKEN-exactly at temperature > 0 too."""
    off = make_engine(tiny, prefix_sharing=False, seed=11)
    want = off.generate(PROMPTS, max_new_tokens=10, temperature=0.8,
                        top_k=20)
    off.close()
    on = make_engine(tiny, seed=11)
    # warm the cache first: the SECOND call must still sample the second
    # call's stream (call-level key advance) while riding cached pages
    on.generate(PROMPTS, max_new_tokens=10, temperature=0.8, top_k=20)
    off2 = make_engine(tiny, prefix_sharing=False, seed=11)
    off2.generate(PROMPTS, max_new_tokens=10, temperature=0.8, top_k=20)
    want2 = off2.generate(PROMPTS, max_new_tokens=10, temperature=0.8,
                          top_k=20)
    off2.close()
    got2 = on.generate(PROMPTS, max_new_tokens=10, temperature=0.8,
                       top_k=20)
    assert got2 == want2 and want2 != want
    on.close()


def test_fused_multi_task_batch_shares_per_task_group(tiny):
    """Regression for the fleet fusion hole: four task templates in ONE
    batch defeat a whole-batch LCP (it is ~0), but the radix cache still
    shares >= 1 page per task group — each group's later prompts hit the
    pages its first prompt inserted."""
    tok = ByteTokenizer()
    # the premise: global LCP shares no full page
    encs = [tok.encode(p) for p in FUSED]
    lcp = 0
    while all(len(e) > lcp and e[lcp] == encs[0][lcp] for e in encs):
        lcp += 1
    assert lcp < PAGE, "templates must not share a page globally"

    eng = make_engine(tiny, slots=4)
    off = make_engine(tiny, prefix_sharing=False, slots=4)
    want = off.generate(FUSED, max_new_tokens=6, temperature=0.0)
    off.close()
    got = eng.generate(FUSED, max_new_tokens=6, temperature=0.0)
    assert got == want
    # per group: 2 non-first prompts × >= 1 template page each
    n_groups = len(TASK_TEMPLATES)
    assert eng.stats.prefix_hit_tokens >= n_groups * 2 * PAGE
    # and every group's template really is cached: a fresh lookup of each
    # group's prompt matches at least one page
    for t in TASK_TEMPLATES:
        assert eng.prefix_cache.match_len(tok.encode(t + "tail_0")) >= PAGE
    eng.close()


def test_single_prompt_serve_mode_consults_cache(tiny):
    """A 1-prompt generate() (serve shape) must ride the cache: the old
    engine bailed at len(encoded) < 2 even with the template KV hot."""
    off = make_engine(tiny, prefix_sharing=False)
    want = [off.generate([p], max_new_tokens=6, temperature=0.0)[0]
            for p in PROMPTS]
    off.close()
    eng = make_engine(tiny)
    got0 = eng.generate([PROMPTS[0]], max_new_tokens=6, temperature=0.0)
    cold = eng.stats.prefill_tokens
    got1 = eng.generate([PROMPTS[1]], max_new_tokens=6, temperature=0.0)
    second = eng.stats.prefill_tokens - cold
    assert [got0[0], got1[0]] == want[:2]
    # the second single-prompt call prefilled only its tail past the
    # shared template
    assert second < 0.5 * cold, (second, cold)
    assert eng.stats.prefix_hit_tokens > 0
    eng.close()


# ---------------------------------------------------------------------------
# pressure: eviction, admission, preemption
# ---------------------------------------------------------------------------

def test_eviction_under_tiny_pool_keeps_outputs_exact(tiny):
    """Distinct-prefix prompts through a pool too small to cache them all:
    LRU nodes must be evicted (counter > 0), decode must stay admitted,
    outputs must equal the cache-off run."""
    prompts = [(("# %02d\n" % i) * 12) + f"x{i}" for i in range(6)]
    off = make_engine(tiny, prefix_sharing=False, max_seq_len=256)
    want = off.generate(prompts, max_new_tokens=6, temperature=0.0)
    off.close()
    # 13 usable pages, 2 slots; each ~5-page prompt caches ~4 pages →
    # six distinct prefixes cannot coexist
    eng = make_engine(tiny, max_seq_len=256, num_pages=14)
    got = eng.generate(prompts, max_new_tokens=6, temperature=0.0)
    assert got == want
    assert eng.stats.prefix_evictions > 0
    # conservation: every page is free, cached, or the trash page
    assert eng.rt.free_pages + eng.prefix_cache.cached_pages \
        == eng.num_pages - 1
    assert eng.prefix_cache.pinned_pages == 0
    eng.close()


def test_preemption_of_rider_with_cached_prefix(tiny):
    """Preemption × cached prefix: a rider preempted mid-decode must
    re-attach its cached prefix pages at re-admission and finish with the
    uncontended outputs."""
    import types

    prompts = [TEMPLATE + t for t in ["a = 1", "b = 2"]]
    roomy = make_engine(tiny, max_seq_len=256)
    want = roomy.generate(prompts, max_new_tokens=40, temperature=0.0)
    roomy.close()
    # template ≈ 9 pages cached + 2 riders × (tail+generated) pages on a
    # 15-page pool: decode growth must preempt (the cached template is
    # pinned by live riders, so eviction alone cannot save it)
    tight = make_engine(tiny, max_seq_len=256, num_pages=16)
    resumed = []
    orig = tight._prefill_admitted

    def spy(self, admitted, reqs):
        resumed.extend(s for s, _ in admitted if reqs[s].generated)
        return orig(admitted, reqs)

    tight._prefill_admitted = types.MethodType(spy, tight)
    got = tight.generate(prompts, max_new_tokens=40, temperature=0.0)
    assert got == want
    assert resumed, "pool was sized to force a preemption"
    eng_tok = ByteTokenizer()
    assert tight.prefix_cache.match_len(
        eng_tok.encode(prompts[0])) >= PAGE   # cache survived the squeeze
    tight.close()


def test_admission_evicts_idle_cache_instead_of_deadlocking(tiny):
    """A cache-filled pool must yield pages to admission: submit a prompt
    whose pages only fit if rider-free cached nodes are evicted."""
    eng = make_engine(tiny, max_seq_len=256, num_pages=14)
    # fill the cache with a distinct prefix, then release all riders
    eng.generate([("# warm\n" * 14) + "q"], max_new_tokens=4,
                 temperature=0.0)
    assert eng.prefix_cache.cached_pages > 0
    # a fat unrelated prompt now needs most of the pool
    out = eng.generate([("z" * 150) + " end"], max_new_tokens=4,
                       temperature=0.0)
    assert len(out) == 1 and isinstance(out[0], str)
    eng.close()


# ---------------------------------------------------------------------------
# dp / tp / session parity
# ---------------------------------------------------------------------------

def test_dp_replicas_cache_parity(tiny):
    import jax

    from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    cfg, params = tiny
    single = make_engine(tiny, prefix_sharing=False)
    want = single.generate(PROMPTS, max_new_tokens=6, temperature=0.0)
    single.close()
    dpp = DataParallelPagedEngine(params, cfg, ByteTokenizer(), dp_size=2,
                                  tp_size=1, max_slots=2, page_size=PAGE,
                                  max_seq_len=512)
    got1 = dpp.generate(PROMPTS, max_new_tokens=6, temperature=0.0)
    cold = dpp.stats.prefill_tokens
    got2 = dpp.generate(PROMPTS, max_new_tokens=6, temperature=0.0)
    warm = dpp.stats.prefill_tokens - cold
    assert got1 == want and got2 == want
    # each replica caches its own template copy; the repeat hits both
    assert warm < cold
    assert dpp.prefix_cache_counters()["cached_pages"] > 0
    dpp.close()


def test_tp_sharded_engine_cache_parity(tiny):
    """tp=2 mesh: the gathered prefix context rides the sharded pool; the
    warm repeat must match the unsharded engine bit-exactly."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    cfg, params = tiny
    from reval_tpu.parallel import make_mesh

    single = make_engine(tiny, prefix_sharing=False)
    want = single.generate(PROMPTS, max_new_tokens=4, temperature=0.0)
    single.close()
    mesh = make_mesh(tp=2)
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                         page_size=PAGE, max_seq_len=512, mesh=mesh)
    got1 = eng.generate(PROMPTS, max_new_tokens=4, temperature=0.0)
    cold = eng.stats.prefill_tokens
    got2 = eng.generate(PROMPTS, max_new_tokens=4, temperature=0.0)
    warm = eng.stats.prefill_tokens - cold
    assert got1 == want and got2 == want
    assert warm < 0.5 * cold
    eng.close()


def test_session_cache_persists_across_submissions(tiny):
    from reval_tpu.serving.session import ContinuousSession

    off = make_engine(tiny, prefix_sharing=False)
    want = [off.generate([p], max_new_tokens=6, temperature=0.0)[0]
            for p in PROMPTS[:2]]
    off.close()
    eng = make_engine(tiny)
    with ContinuousSession(eng) as sess:
        a = sess.submit([PROMPTS[0]], max_new_tokens=6).result(120)
        cold = eng.stats.prefill_tokens
        b = sess.submit([PROMPTS[1]], max_new_tokens=6).result(120)
        warm = eng.stats.prefill_tokens - cold
    assert a + b == want
    assert warm < 0.5 * cold, (warm, cold)
    eng.close()


# ---------------------------------------------------------------------------
# tool smoke
# ---------------------------------------------------------------------------

def test_prefix_stats_tool_smoke():
    import json

    r = subprocess.run([sys.executable, "tools/prefix_stats.py", "--tiny"],
                       cwd=REPO, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.strip()][-1]
    d = json.loads(line)
    assert d["metric"] == "prefix_overlap"
    assert set(d["tasks"]) == {"coverage", "path", "state", "output"}
    for row in d["tasks"].values():
        assert 0 < row["template_share"] <= 1
        assert row["warm_hit_rate"] >= row["cold_hit_rate"]
        assert row["distinct_pages"] > 0
    # the fused batch itself shares (almost) nothing globally — the very
    # reason per-task grouping feeds the radix lookup
    assert d["fused_batch_lcp_tokens"] < d["page_size"]
    assert 0 < d["warm_hit_rate"] <= 1
