"""Paged-cache forward parity: prefill → commit → paged decode must produce
the same logits as the contiguous left-padded cache path (test_models'
oracle), for sequences of different lengths sharing one page pool."""

import pytest

pytestmark = pytest.mark.slow

import jax.numpy as jnp
import numpy as np

from reval_tpu.models import ModelConfig, decode_step, init_kv_cache, init_random_params, prefill
from reval_tpu.models.paged import commit_prefill, init_paged_cache, paged_decode_step

PAGE = 128


def small_cfg():
    return ModelConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_layers=2, num_heads=4, num_kv_heads=2, head_dim=128)


def test_paged_decode_matches_contiguous():
    cfg = small_cfg()
    params = init_random_params(cfg, seed=0, dtype="float32")
    b, t = 2, PAGE  # one-page prefill bucket
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, t)), jnp.int32)
    pad_len = jnp.asarray([5, 100], jnp.int32)   # lengths 123 and 28

    # contiguous reference
    cache = init_kv_cache(cfg, b, t + 8, dtype=jnp.float32)
    logits_ref, cache = prefill(params, cfg, tokens, pad_len, cache)

    # paged: commit the prefill, then decode step by step
    max_pages = 3
    pcache = init_paged_cache(cfg, num_pages=1 + b * max_pages, page_size=PAGE,
                              dtype=jnp.float32)
    # seq 0 → pages [1, 2], seq 1 → pages [3, 4]; slot for the prefill
    # bucket (1 page) is the first column; the rest pad with trash page 0
    tables = jnp.asarray([[1, 2, 0], [3, 4, 0]], jnp.int32)
    prefill_kv = type(cache)(cache.k[:, :, :t], cache.v[:, :, :t])
    pcache = commit_prefill(pcache, prefill_kv, pad_len, tables[:, :1])
    seq_lens = t - pad_len

    nxt = jnp.argmax(logits_ref[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
    cur_pos = jnp.int32(t)
    for _ in range(4):
        ref_logits, cache = decode_step(params, cfg, nxt, pad_len, cache, cur_pos)
        paged_logits, pcache = paged_decode_step(params, cfg, nxt, tables,
                                                 seq_lens, pcache)
        np.testing.assert_allclose(np.asarray(paged_logits),
                                   np.asarray(ref_logits), rtol=2e-4, atol=2e-4)
        nxt = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)[:, None]
        cur_pos = cur_pos + 1
        seq_lens = seq_lens + 1


def test_idle_slot_is_harmless():
    """An idle slot (trash table, len 1) must not perturb active slots."""
    cfg = small_cfg()
    params = init_random_params(cfg, seed=1, dtype="float32")
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, PAGE)), jnp.int32)
    pad_len = jnp.zeros(1, jnp.int32)
    cache = init_kv_cache(cfg, 1, PAGE, dtype=jnp.float32)
    _, cache = prefill(params, cfg, tokens, pad_len, cache)

    pcache = init_paged_cache(cfg, num_pages=4, page_size=PAGE, dtype=jnp.float32)
    tables1 = jnp.asarray([[1, 2]], jnp.int32)
    pcache1 = commit_prefill(pcache, cache, pad_len, tables1[:, :1])
    solo, _ = paged_decode_step(
        params, cfg, jnp.asarray([[7]], jnp.int32), tables1,
        jnp.asarray([PAGE], jnp.int32), pcache1)

    # same sequence in slot 0 + an idle slot 1
    tables2 = jnp.asarray([[1, 2], [0, 0]], jnp.int32)
    kv2 = type(cache)(jnp.tile(cache.k, (1, 2, 1, 1, 1)),
                      jnp.tile(cache.v, (1, 2, 1, 1, 1)))
    pcache2 = commit_prefill(
        init_paged_cache(cfg, num_pages=4, page_size=PAGE, dtype=jnp.float32),
        type(cache)(kv2.k.at[:, 1].set(0), kv2.v.at[:, 1].set(0)),
        jnp.asarray([0, 0], jnp.int32), jnp.asarray([[1], [0]], jnp.int32))
    duo, _ = paged_decode_step(
        params, cfg, jnp.asarray([[7], [3]], jnp.int32), tables2,
        jnp.asarray([PAGE, 1], jnp.int32), pcache2)
    np.testing.assert_allclose(np.asarray(duo[0]), np.asarray(solo[0]),
                               rtol=2e-4, atol=2e-4)
