"""Open-loop load generator: seeded arrival processes, per-tenant
workload mixes, the runner's complete ledger + artifact schema, and the
``obs_report --slo`` cross-round diff.

Everything host-only: the runner fires at mock ``serve --mock`` fleets
(directly or through a router); the arrival/workload pieces are pure
and seeded, so reproducibility is asserted bit-for-bit.
"""

import json
import os
import random
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

from loadgen import (  # noqa: E402
    OpenLoopRunner, build_workload, diurnal_arrivals, diurnal_rate,
    parse_tenant_weights, poisson_arrivals, reval_tenants,
    synthetic_tenants)
from reval_tpu.obs.metrics import snapshot_fraction_le  # noqa: E402
from reval_tpu.serving import FleetRouter, serve_config  # noqa: E402


def make_replica(port=0, **cfg):
    base = {"mock": True, "mock_echo": True}
    base.update(cfg)
    return serve_config(base, port=port).start()


def wait_ready(router, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.readiness()["ready"]:
            return
        time.sleep(0.02)
    raise AssertionError("router never became ready")


# ---------------------------------------------------------------------------
# Arrival processes: seeded, bit-reproducible, the right shapes
# ---------------------------------------------------------------------------

def test_poisson_arrivals_bit_reproducible_and_rate_shaped():
    a = poisson_arrivals(50.0, 4.0, random.Random(7))
    b = poisson_arrivals(50.0, 4.0, random.Random(7))
    assert a == b                           # bit-identical under one seed
    assert a != poisson_arrivals(50.0, 4.0, random.Random(8))
    assert all(0.0 <= t < 4.0 for t in a)
    assert a == sorted(a)
    # ~200 expected; 4 sigma ≈ 57
    assert 120 <= len(a) <= 280, len(a)


def test_diurnal_arrivals_bit_reproducible_with_peak_mid_run():
    a = diurnal_arrivals(2.0, 60.0, 4.0, random.Random(3))
    b = diurnal_arrivals(2.0, 60.0, 4.0, random.Random(3))
    assert a == b
    trough = sum(1 for t in a if t < 1.0)
    peak = sum(1 for t in a if 1.5 <= t < 2.5)
    assert peak > 2 * trough, (trough, peak)
    # the rate curve itself: trough at 0, peak at period/2
    assert diurnal_rate(0.0, 2.0, 60.0, 4.0) == pytest.approx(2.0)
    assert diurnal_rate(2.0, 2.0, 60.0, 4.0) == pytest.approx(60.0)


def test_workload_is_seeded_weighted_and_template_prefixed():
    arrivals = poisson_arrivals(40.0, 4.0, random.Random(1))
    tenants = synthetic_tenants(parse_tenant_weights("alpha:3,beta:1"),
                                deadline_s=9.0, template_chars=500)
    reqs = build_workload(arrivals, tenants, random.Random(5))
    reqs2 = build_workload(
        arrivals, synthetic_tenants({"alpha": 3, "beta": 1},
                                    deadline_s=9.0, template_chars=500),
        random.Random(5))
    assert [(r.tenant, r.prompt) for r in reqs] == \
        [(r.tenant, r.prompt) for r in reqs2]
    by_tenant = {"alpha": 0, "beta": 0}
    for r in reqs:
        by_tenant[r.tenant] += 1
        assert r.deadline_s == 9.0
        # the synthetic template prefix is long enough to carry a router
        # affinity key, and the probe suffix keeps prompts distinct
        assert len(r.prompt) >= 500
        assert f"probe {r.seq}" in r.prompt
    # 3:1 mix, loosely (seeded, so this is stable for THIS seed)
    assert by_tenant["alpha"] > 2 * by_tenant["beta"], by_tenant
    # distinct prompts share their (tenant, task) template prefix
    alpha_cov = [r.prompt for r in reqs
                 if r.tenant == "alpha" and "[coverage::alpha]" in r.prompt]
    assert len(alpha_cov) >= 2
    assert alpha_cov[0][:400] == alpha_cov[1][:400]


def test_reval_workload_samples_genuine_planned_prompts():
    tenants = reval_tenants({"solo": 1.0}, dataset="humaneval",
                            prompt_type="direct", per_task=2)
    pools = tenants[0].pools
    assert set(pools) == {"coverage", "path", "state", "output"}
    for task, prompts in pools.items():
        assert prompts and all(isinstance(p, str) and p for p in prompts)
    reqs = build_workload([0.0, 0.1, 0.2, 0.3], tenants, random.Random(2))
    # genuine prompts pass through verbatim (no probe suffix): replays
    # of the same pools are exact REval request shapes
    all_prompts = {p for prompts in pools.values() for p in prompts}
    assert all(r.prompt in all_prompts for r in reqs)


# ---------------------------------------------------------------------------
# The runner: complete ledger, artifact schema, open-loop property
# ---------------------------------------------------------------------------

def test_runner_artifact_schema_and_complete_ledger():
    srv = make_replica()
    router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                         health_interval_s=0.05).start()
    try:
        wait_ready(router)
        arrivals = poisson_arrivals(40.0, 1.0, random.Random(11))
        tenants = synthetic_tenants({"alpha": 3, "beta": 1},
                                    deadline_s=10.0)
        reqs = build_workload(arrivals, tenants, random.Random(11))
        runner = OpenLoopRunner(f"127.0.0.1:{router.port}", reqs,
                                concurrency=32, slo_e2e_s=5.0,
                                timeline_bucket_s=0.5)
        art = runner.run()
    finally:
        router.shutdown()
        srv.shutdown()
    assert art["format"] == "reval-loadgen-v1"
    assert art["ledger_complete"] is True
    assert art["requests"] == len(reqs)
    assert art["counts"]["lost"] == 0
    assert art["goodput"]["good"] == len(reqs)
    assert art["goodput"]["ratio"] == 1.0
    assert art["slo"]["attainment"]["e2e"] == 1.0
    assert art["slo"]["latency"]["e2e"]["p99"] >= \
        art["slo"]["latency"]["e2e"]["p50"]
    # fleet-side blocks came from the federated /metrics diff
    assert art["counts"]["goodput_total"] == len(reqs)
    assert "ttft" in art["slo"]["latency"]
    # timeline accounting: every arrival and completion landed in a bucket
    assert sum(row["arrivals"] for row in art["timeline"]) == len(reqs)
    assert sum(row["completions"] for row in art["timeline"]) == len(reqs)
    assert art["recovery"]["worst_bad_window_s"] == 0.0
    per_tenant = art["tenants"]
    assert set(per_tenant) == {"alpha", "beta"}
    assert sum(t["requests"] for t in per_tenant.values()) == len(reqs)


def test_runner_is_open_loop_under_a_slow_fleet():
    """A fleet too slow for the offered load must yield misses/losses in
    the artifact — never a stretched run: the arrival schedule is fixed
    up front and the wall clock stays bounded by schedule + deadline."""
    srv = make_replica(mock_step_s=0.2, max_queued_tokens=1)
    router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                         health_interval_s=0.05).start()
    try:
        wait_ready(router)
        arrivals = [i * 0.05 for i in range(12)]    # 20/s vs ~3/s capacity
        tenants = synthetic_tenants({"solo": 1.0}, deadline_s=1.0,
                                    template_chars=120)
        reqs = build_workload(arrivals, tenants, random.Random(4))
        runner = OpenLoopRunner(f"127.0.0.1:{router.port}", reqs,
                                concurrency=32, timeline_bucket_s=0.5)
        t0 = time.monotonic()
        art = runner.run()
        wall = time.monotonic() - t0
    finally:
        router.shutdown()
        srv.shutdown()
    # open loop: the whole run is schedule (0.55s) + deadline (1s) + slack,
    # NOT 12 × 0.6s of serialized service time
    assert wall < 6.0, wall
    assert art["ledger_complete"] is True
    assert art["requests"] == 12
    # the slow fleet is VISIBLE: losses (deadline) and/or sheds happened,
    # and the recovery block flags bad buckets
    assert art["counts"]["lost"] > 0 or art["counts"]["shed_429"] > 0
    if art["counts"]["lost"]:
        assert art["recovery"]["bad_buckets"] > 0
        assert art["recovery"]["worst_bad_window_s"] > 0


def test_loadgen_cli_end_to_end(tmp_path):
    srv = make_replica()
    try:
        out_path = tmp_path / "loadgen.json"
        r = subprocess.run(
            [sys.executable, "tools/loadgen.py",
             "--target", f"127.0.0.1:{srv.port}",
             "--workload", "synthetic", "--process", "diurnal",
             "--trough-rate", "5", "--peak-rate", "30",
             "--duration", "1.5", "--seed", "9",
             "--tenants", "alpha:2,beta:1", "--deadline", "10",
             "--slo-e2e", "5.0", "--timeline-bucket-s", "0.5",
             "--out", str(out_path)],
            capture_output=True, text=True, timeout=150, cwd=ROOT)
        assert r.returncode == 0, r.stdout + r.stderr
        stdout_art = json.loads(r.stdout.strip().splitlines()[-1])
        file_art = json.loads(out_path.read_text())
        assert file_art["format"] == "reval-loadgen-v1"
        assert file_art["seed"] == 9
        assert file_art["process"] == "diurnal"
        assert stdout_art["goodput"] == file_art["goodput"]
        assert file_art["counts"]["lost"] == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# obs_report --slo: cross-round diff, first regression named
# ---------------------------------------------------------------------------

def _write_round(path, ratio, e2e_att, lost=0, window=0.0):
    art = {"format": "reval-loadgen-v1",
           "goodput": {"ratio": ratio},
           "slo": {"attainment": {"e2e": e2e_att}},
           "counts": {"lost": lost},
           "recovery": {"worst_bad_window_s": window}}
    with open(path, "w") as f:
        json.dump(art, f)


def test_obs_report_slo_names_first_regressed_round(tmp_path):
    paths = [str(tmp_path / f"r{i}.json") for i in range(4)]
    _write_round(paths[0], 0.99, 0.99)
    _write_round(paths[1], 0.995, 1.0)
    _write_round(paths[2], 0.90, 0.93, lost=3, window=2.5)   # regression
    _write_round(paths[3], 0.91, 0.94)
    r = subprocess.run(
        [sys.executable, "tools/obs_report.py", "--slo", *paths],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "first regression: r2.json" in r.stdout
    assert "goodput" in r.stdout and "e2e" in r.stdout
    assert "r3.json" in r.stdout
    # clean trajectory: no regression named
    r2 = subprocess.run(
        [sys.executable, "tools/obs_report.py", "--slo",
         paths[0], paths[1]],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r2.returncode == 0
    assert "no goodput/attainment regression" in r2.stdout


def test_snapshot_fraction_le_matches_bucket_model():
    hist = {"buckets": [[0.1, 2], [0.5, 2], [1.0, 0]], "inf": 1,
            "count": 5}
    assert snapshot_fraction_le(hist, 0.1) == pytest.approx(0.4)
    assert snapshot_fraction_le(hist, 0.5) == pytest.approx(0.8)
    # interpolated inside the (0.1, 0.5] bucket
    assert snapshot_fraction_le(hist, 0.3) == pytest.approx(0.6)
    assert snapshot_fraction_le(hist, 100.0) == pytest.approx(0.8)
    assert snapshot_fraction_le({"buckets": [], "count": 0}, 1.0) == 1.0
