"""Top-k / nucleus (top-p) sampling: filter semantics and engine wiring.

The filter follows vLLM/OpenAI semantics: keep the top-k most probable
tokens intersected with the smallest probability-sorted prefix reaching
top_p mass (the crossing token kept).  Engines compile the filter into
the decode program ONLY when some request in the batch asks for it — the
default program carries no [B, V] sort.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from reval_tpu.inference.tpu.sampling import filter_logits


class TestFilterLogits:
    LOGITS = jnp.asarray([[0.0, 1.0, 2.0, 3.0, -1.0],
                          [5.0, 4.0, 3.0, 2.0, 1.0]], jnp.float32)

    def kept(self, out):
        return (np.asarray(out) > -1e29).tolist()

    def test_top_k(self):
        out = filter_logits(self.LOGITS, jnp.asarray([2, 2]),
                            jnp.asarray([1.0, 1.0]))
        assert self.kept(out) == [[False, False, True, True, False],
                                  [True, True, False, False, False]]

    def test_top_p_keeps_crossing_token(self):
        # row 1 softmax ≈ [.64, .24, .09, ...]; p=0.7 crosses at the 2nd
        out = filter_logits(self.LOGITS, jnp.asarray([0, 0]),
                            jnp.asarray([1.0, 0.7]))
        assert self.kept(out)[0] == [True] * 5          # off for row 0
        assert self.kept(out)[1] == [True, True, False, False, False]

    def test_tiny_top_p_keeps_argmax_only(self):
        out = filter_logits(self.LOGITS, jnp.asarray([0, 0]),
                            jnp.asarray([1e-9, 1e-9]))
        assert np.sum(self.kept(out)) == 2

    def test_defaults_are_identity(self):
        out = filter_logits(self.LOGITS, jnp.asarray([0, 0]),
                            jnp.asarray([1.0, 1.0]))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(self.LOGITS))

    def test_intersection(self):
        # top_k=3 ∩ top_p tiny → 1 per row
        out = filter_logits(self.LOGITS, jnp.asarray([3, 3]),
                            jnp.asarray([1e-9, 1e-9]))
        assert np.sum(self.kept(out)) == 2

    def test_under_jit_per_row_mix(self):
        out = jax.jit(filter_logits)(self.LOGITS, jnp.asarray([2, 0]),
                                     jnp.asarray([1.0, 0.7]))
        assert self.kept(out) == [[False, False, True, True, False],
                                  [True, True, False, False, False]]


@pytest.mark.slow
class TestEngineWiring:
    def _setup(self, seed=11):
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.models import ModelConfig, init_random_params

        cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=4, num_kv_heads=2,
                          head_dim=16)
        return (init_random_params(cfg, seed=seed, dtype="float32"), cfg,
                ByteTokenizer())

    def test_static_top_k1_equals_greedy(self):
        # top_k=1 leaves only the argmax → any temperature samples it
        from reval_tpu.inference.tpu.engine import TPUEngine

        params, cfg, tok = self._setup()
        eng = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=256)
        prompts = ["def f(x):", "x = 1"]
        greedy = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        hot = eng.generate(prompts, max_new_tokens=8, temperature=2.0,
                           top_k=1)
        assert hot == greedy

    def test_paged_top_k1_equals_greedy(self):
        from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

        params, cfg, tok = self._setup()
        eng = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=128,
                             max_seq_len=256)
        prompts = ["def f(x):", "x = 1"]
        greedy = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        hot = eng.generate(prompts, max_new_tokens=8, temperature=2.0,
                           top_k=1)
        assert hot == greedy
        eng.close()

    def test_paged_top_p_changes_distribution(self):
        # same request keys, same temperature: a binding nucleus must be
        # able to change sampled text (and a non-binding one must not)
        from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

        params, cfg, tok = self._setup(seed=12)
        eng = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=128,
                             max_seq_len=256, seed=7)
        prompts = ["def g(y):", "while True:"]
        off = eng.generate(prompts, max_new_tokens=16, temperature=1.5)
        eng2 = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=128,
                              max_seq_len=256, seed=7)
        noop = eng2.generate(prompts, max_new_tokens=16, temperature=1.5,
                             top_p=1.0)
        assert noop == off          # top_p=1 is exactly the unfiltered path
        eng3 = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=128,
                              max_seq_len=256, seed=7)
        tight = eng3.generate(prompts, max_new_tokens=16, temperature=1.5,
                              top_p=0.05)
        assert tight != off         # random weights: flat logits, tiny
        eng.close(); eng2.close(); eng3.close()   # nucleus binds hard

    def test_session_forwards_sampling(self):
        from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
        from reval_tpu.serving.session import ContinuousSession

        params, cfg, tok = self._setup()
        eng = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=128,
                             max_seq_len=256)
        greedy = eng.generate(["def f(x):"], max_new_tokens=8,
                              temperature=0.0)
        with ContinuousSession(eng) as session:
            got = session.submit(["def f(x):"], max_new_tokens=8,
                                 temperature=2.0, top_k=1).result()
        assert got == greedy


    def test_dp_paged_forwards_sampling(self):
        from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine

        params, cfg, tok = self._setup()
        eng = DataParallelPagedEngine(params, cfg, tok, dp_size=2, tp_size=1,
                                      max_slots=2, page_size=128,
                                      max_seq_len=256)
        prompts = ["def f(x):", "x = 1", "y = 2", "while y:"]
        greedy = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        hot = eng.generate(prompts, max_new_tokens=8, temperature=2.0,
                           top_k=1)
        assert hot == greedy
        eng.close()
