"""Resilience layer: retry/backoff schedule (injected clock — no real
sleeps), wait-for-server handshake, chaos determinism, batch bisection,
crash-resumable fleet checkpoints, and the CLI chaos smoke target."""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from reval_tpu.fleet import FleetRunner
from reval_tpu.inference.mock import MockBackend
from reval_tpu.resilience import (
    INFER_FAILED,
    ChaosBackend,
    FleetCheckpoint,
    ResilientBackend,
    RetryPolicy,
    retryable_error,
    wait_for_server,
)


def _no_sleep_policy(**kw):
    kw.setdefault("jitter", 0.0)
    return RetryPolicy(sleep=lambda s: None, **kw)


class EchoBackend:
    """Minimal infer_many backend for wrapper tests."""

    info = "echo_model_direct_temp0.0"
    prompt_type = "direct"

    def __init__(self):
        self.batches = []

    def infer_many(self, prompts):
        self.batches.append(list(prompts))
        return [f"echo:{p}" for p in prompts]


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_backoff_schedule_exponential_no_jitter():
    sleeps = []
    policy = RetryPolicy(max_attempts=4, base_delay=1.0, multiplier=2.0,
                         jitter=0.0, sleep=sleeps.append)
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 4:
            raise ConnectionResetError("transient")
        return "ok"

    assert policy.call(flaky) == "ok"
    assert sleeps == [1.0, 2.0, 4.0]
    assert attempts["n"] == 4


def test_backoff_caps_at_max_delay_and_jitter_is_bounded():
    import random

    sleeps = []
    policy = RetryPolicy(max_attempts=6, base_delay=1.0, multiplier=4.0,
                         max_delay=5.0, jitter=0.5, sleep=sleeps.append,
                         rng=random.Random(0))
    with pytest.raises(TimeoutError):
        policy.call(lambda: (_ for _ in ()).throw(TimeoutError("always")))
    assert len(sleeps) == 5
    for i, s in enumerate(sleeps):
        base = min(1.0 * 4.0 ** i, 5.0)
        assert base <= s <= base * 1.5
    # seeded rng ⇒ the schedule itself is reproducible
    sleeps2 = []
    policy2 = RetryPolicy(max_attempts=6, base_delay=1.0, multiplier=4.0,
                          max_delay=5.0, jitter=0.5, sleep=sleeps2.append,
                          rng=random.Random(0))
    with pytest.raises(TimeoutError):
        policy2.call(lambda: (_ for _ in ()).throw(TimeoutError("always")))
    assert sleeps2 == sleeps


def test_non_retryable_raises_immediately():
    policy = _no_sleep_policy(max_attempts=5)
    attempts = {"n": 0}

    def bad_request():
        attempts["n"] += 1
        raise ValueError("application bug")

    with pytest.raises(ValueError):
        policy.call(bad_request)
    assert attempts["n"] == 1


def test_attempts_override():
    policy = _no_sleep_policy(max_attempts=5)
    attempts = {"n": 0}

    def always():
        attempts["n"] += 1
        raise TimeoutError("x")

    with pytest.raises(TimeoutError):
        policy.call(always, attempts=2)
    assert attempts["n"] == 2


def test_retryable_error_classification():
    assert retryable_error(urllib.error.URLError("refused"))
    assert retryable_error(TimeoutError())
    assert retryable_error(socket.timeout())
    assert retryable_error(ConnectionResetError())
    assert retryable_error(json.JSONDecodeError("truncated", "{", 1))
    assert retryable_error(urllib.error.HTTPError("u", 503, "busy", None, None))
    assert retryable_error(urllib.error.HTTPError("u", 500, "ise", None, None))
    assert not retryable_error(urllib.error.HTTPError("u", 400, "bad", None, None))
    assert not retryable_error(urllib.error.HTTPError("u", 404, "nope", None, None))
    assert not retryable_error(ValueError("bug"))


# ---------------------------------------------------------------------------
# wait_for_server
# ---------------------------------------------------------------------------

def test_wait_for_server_polls_until_up():
    clock = {"t": 0.0}
    probes = {"n": 0}

    def probe():
        probes["n"] += 1
        if probes["n"] < 4:
            raise urllib.error.URLError("connection refused")
        return {"status": "ok"}

    out = wait_for_server(probe, timeout=60.0, interval=0.5,
                          clock=lambda: clock["t"],
                          sleep=lambda s: clock.__setitem__("t", clock["t"] + s))
    assert out == {"status": "ok"}
    assert probes["n"] == 4


def test_wait_for_server_http_error_means_up():
    """An old server without /healthz answers 404 — that's still up."""
    def probe():
        raise urllib.error.HTTPError("u", 404, "no such route", None, None)

    assert wait_for_server(probe, timeout=1.0, clock=lambda: 0.0,
                           sleep=lambda s: None) is None


def test_wait_for_server_times_out():
    clock = {"t": 0.0}

    def probe():
        raise urllib.error.URLError("connection refused")

    with pytest.raises(TimeoutError, match="not reachable"):
        wait_for_server(probe, timeout=5.0, interval=1.0,
                        clock=lambda: clock["t"],
                        sleep=lambda s: clock.__setitem__("t", clock["t"] + s))


# ---------------------------------------------------------------------------
# ChaosBackend
# ---------------------------------------------------------------------------

def _chaos(seed, rate=0.5, **kw):
    kw.setdefault("sleep", lambda s: None)
    return ChaosBackend(EchoBackend(), rate=rate, seed=seed, **kw)


def test_chaos_is_deterministic_under_a_fixed_seed():
    prompts = [f"prompt-{i}" for i in range(24)]
    runs = []
    for _ in range(2):
        chaos = _chaos(seed=7)
        backend = ResilientBackend(chaos, policy=_no_sleep_policy(),
                                   progress=False)
        runs.append((backend.infer_many(prompts), list(chaos.injected)))
    assert runs[0] == runs[1]
    assert runs[0][1], "rate 0.5 over 24 prompts must inject something"


def test_chaos_schedule_is_call_order_independent():
    """However the caller slices the batch, each prompt's fault schedule
    is the same — bisection can't change what gets injected."""
    prompts = [f"p{i}" for i in range(8)]
    per_prompt = {}
    for p in prompts:
        chaos = _chaos(seed=3)
        per_prompt[p] = chaos._schedule(p)
    chaos = _chaos(seed=3)
    assert {p: chaos._schedule(p) for p in reversed(prompts)} == per_prompt


def test_chaos_rearms_across_repeats():
    """A successful serve re-arms the prompt's schedule: the fleet's later
    repeats are still exercised, not silently chaos-free."""
    chaos = _chaos(seed=11, rate=0.5)
    backend = ResilientBackend(chaos, policy=_no_sleep_policy(), progress=False)
    prompts = [f"r{i}" for i in range(12)]
    backend.infer_many(prompts)
    first = len(chaos.injected)
    backend.infer_many(prompts)          # same prompts: repeat 2
    assert first > 0
    assert len(chaos.injected) > first, "repeat 2 must inject fresh faults"


def test_chaos_faults_are_transient():
    """Fault budgets are finite: enough bare retries always drain them."""
    chaos = _chaos(seed=1, rate=0.6)
    for prompt in (f"q{i}" for i in range(10)):
        for _ in range(10):
            try:
                out = chaos.infer_many([prompt])
                break
            except Exception as exc:
                assert retryable_error(exc)
        assert out == [f"echo:{prompt}"]


# ---------------------------------------------------------------------------
# ResilientBackend: bisection
# ---------------------------------------------------------------------------

def test_bisection_isolates_a_permanently_poisoned_prompt():
    class Poisoned(EchoBackend):
        def infer_many(self, prompts):
            if any(p == "BAD" for p in prompts):
                raise TimeoutError("poisoned batch")
            return super().infer_many(prompts)

    prompts = [f"p{i}" for i in range(6)] + ["BAD"] + [f"p{i}" for i in range(6, 10)]
    backend = ResilientBackend(Poisoned(), policy=_no_sleep_policy(),
                               progress=False)
    out = backend.infer_many(prompts)
    assert len(out) == len(prompts)
    for prompt, resp in zip(prompts, out):
        assert resp == (INFER_FAILED if prompt == "BAD" else f"echo:{prompt}")
    assert len(backend.failures) == 1
    assert backend.failures[0]["prompt"] == "BAD"


def test_zero_loss_under_transient_chaos():
    prompts = [f"prompt-{i}" for i in range(40)]
    chaos = _chaos(seed=11, rate=0.3)
    backend = ResilientBackend(chaos, policy=_no_sleep_policy(), progress=False)
    out = backend.infer_many(prompts)
    assert out == [f"echo:{p}" for p in prompts]
    assert backend.failures == []
    assert chaos.injected, "rate 0.3 over 40 prompts must inject something"


def test_short_response_list_is_a_contract_error_not_repaired():
    class Short(EchoBackend):
        def infer_many(self, prompts):
            return ["only-one"]

    backend = ResilientBackend(Short(), policy=_no_sleep_policy(), progress=False)
    with pytest.raises(RuntimeError, match="contract violation"):
        backend.infer_many(["a", "b", "c"])


def test_systemic_failure_aborts_instead_of_sentineling_everything():
    """A deterministic error hitting every prompt (server upgrade broke the
    protocol) is a systemic failure: abort with the real error instead of
    'completing' with a log full of sentinels."""
    class Broken(EchoBackend):
        def infer_many(self, prompts):
            raise urllib.error.HTTPError("u", 400, "bad request", None, None)

    backend = ResilientBackend(Broken(), policy=_no_sleep_policy(), progress=False)
    with pytest.raises(RuntimeError, match="systemic"):
        backend.infer_many([f"p{i}" for i in range(10)])


def test_wrapper_composes_with_inner_retry_instead_of_multiplying():
    """Wrapping a backend that already retries per request (HTTPClientBackend)
    must not nest the schedules: the wrapper drops to one attempt per level
    and keeps only the bisection."""
    from reval_tpu.inference.client import HTTPClientBackend

    client = HTTPClientBackend(model_id="m", mock=True, temp=0.0,
                               prompt_type="direct")
    backend = ResilientBackend(client, progress=False)
    assert backend.policy.max_attempts == 1
    assert backend.batch_attempts == 1


def test_chaos_between_wrapper_and_client_keeps_full_budget():
    """Chaos faults fire above the HTTP client's retry loop, so the
    client's own policy must not collapse the wrapper's budget — only a
    DIRECT client wrap composes down to one attempt."""
    from reval_tpu.inference.client import HTTPClientBackend

    client = HTTPClientBackend(model_id="m", mock=True, temp=0.0,
                               prompt_type="direct")
    chaos = ChaosBackend(client, rate=0.5, seed=2, sleep=lambda s: None)
    backend = ResilientBackend(chaos, progress=False)
    assert backend.policy.max_attempts > chaos.max_faults_per_prompt


def test_class_sandbox_setup_failure_degrades():
    from reval_tpu.tasks.base import TaskRunner

    class Boom:
        def setUp(self):
            raise OSError("missing fixture file")

    states, status = TaskRunner.run_class_sandbox(Boom, timeout=5)
    assert states is None
    assert status.startswith("exception")


def test_wrapper_delegates_identity():
    inner = MockBackend(prompt_type="direct")
    backend = ResilientBackend(inner, policy=_no_sleep_policy(), progress=False)
    assert backend.info == inner.info
    assert backend.prompt_type == "direct"
    assert backend.infer_one("x") == "mock_model_gen"
    backend.close()


# ---------------------------------------------------------------------------
# FleetCheckpoint
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_and_identity_filter(tmp_path):
    ident = {"model_info": "m_direct", "dataset": "humaneval",
             "prompt_type": "direct"}
    ckpt = FleetCheckpoint(str(tmp_path), ident)
    assert ckpt.load() == 0
    ckpt.record(0, "coverage", {"acc": 1.0})
    ckpt.record(0, "path", {"acc": 0.5})
    fresh = FleetCheckpoint(str(tmp_path), ident)
    assert fresh.load() == 2
    assert fresh.done(0, "coverage") is not None
    assert fresh.done(0, "coverage")["metrics"] == {"acc": 1.0}
    assert fresh.done(1, "coverage") is None
    # a different run identity must not inherit these chunks
    other = FleetCheckpoint(str(tmp_path), {**ident, "prompt_type": "cot"})
    assert other.load() == 0
    # torn trailing line (crash mid-append) is skipped, not fatal
    with open(ckpt.path, "a") as f:
        f.write('{"model_info": "m_direct", "trunc')
    assert FleetCheckpoint(str(tmp_path), ident).load() == 2
    # reset wipes the journal for non-resume runs
    ckpt.reset()
    assert not os.path.exists(ckpt.path)
    assert FleetCheckpoint(str(tmp_path), ident).load() == 0


# ---------------------------------------------------------------------------
# Fleet integration: misalignment guard, chaos fleet, crash + resume
# ---------------------------------------------------------------------------

def _read_task_logs(results_dir, task):
    d = os.path.join(results_dir, f"{task}@mock_model_direct")
    paths = sorted((os.path.join(d, f) for f in os.listdir(d)),
                   key=os.path.getctime)
    return [open(p).read() for p in paths]


def test_fleet_rejects_misaligned_responses_with_task_attribution(tmp_path):
    class Short(EchoBackend):
        def infer_many(self, prompts):
            return ["[ANSWER]x[/ANSWER]"] * (len(prompts) - 1)

    fleet = FleetRunner(dataset="humaneval", repeats=1, backend=Short(),
                        results_dir=str(tmp_path), progress=False,
                        run_consistency=False, max_items=2, resilience=False)
    with pytest.raises(RuntimeError, match="refusing to mis-align"):
        fleet.run()


def test_fleet_completes_under_chaos_with_zero_lost_prompts(tmp_path):
    """The acceptance scenario: 30% transient faults, all repeats finish,
    metrics identical to a chaos-free mock fleet."""
    chaos = ChaosBackend(MockBackend(prompt_type="direct"), rate=0.3, seed=5,
                         sleep=lambda s: None)
    fleet = FleetRunner(dataset="humaneval", repeats=2, backend=chaos,
                        mock=True, results_dir=str(tmp_path / "chaos"),
                        progress=False, max_items=2,
                        retry_policy=_no_sleep_policy())
    result = fleet.run()
    assert len(result["repeats"]) == 2
    assert "lost_prompts" not in result
    assert chaos.injected, "chaos at 0.3 must actually inject faults"
    clean = FleetRunner(dataset="humaneval", repeats=2, mock=True,
                        results_dir=str(tmp_path / "clean"), progress=False,
                        max_items=2)
    assert result["repeats"] == clean.run()["repeats"]


def test_fleet_crash_then_resume_reproduces_identical_logs(tmp_path, monkeypatch):
    from reval_tpu.tasks.base import TaskRunner

    kwargs = dict(dataset="humaneval", repeats=2, mock=True, progress=False,
                  run_consistency=False, max_items=2)

    # uninterrupted reference run
    FleetRunner(results_dir=str(tmp_path / "ref"), **kwargs).run()

    # crash mid-repeat-0, after two of four tasks have scored
    orig = TaskRunner.score_and_write
    scored = {"n": 0}

    def crashing(self, records, jobs, responses):
        if scored["n"] == 2:
            raise RuntimeError("simulated mid-repeat crash")
        scored["n"] += 1
        return orig(self, records, jobs, responses)

    monkeypatch.setattr(TaskRunner, "score_and_write", crashing)
    with pytest.raises(RuntimeError, match="simulated"):
        FleetRunner(results_dir=str(tmp_path / "res"), **kwargs).run()
    monkeypatch.setattr(TaskRunner, "score_and_write", orig)

    ckpt_path = tmp_path / "res" / FleetCheckpoint.FILENAME
    assert ckpt_path.exists()
    assert len(ckpt_path.read_text().splitlines()) == 2  # two chunks survived

    result = FleetRunner(results_dir=str(tmp_path / "res"), resume=True,
                         **kwargs).run()
    assert len(result["repeats"]) == 2
    for task in ("coverage", "path", "state", "output"):
        ref_logs = _read_task_logs(str(tmp_path / "ref"), task)
        res_logs = _read_task_logs(str(tmp_path / "res"), task)
        assert len(res_logs) == 2, task
        assert sorted(res_logs) == sorted(ref_logs), task

    # resuming a *finished* run is a no-op: no new logs appear
    again = FleetRunner(results_dir=str(tmp_path / "res"), resume=True,
                        **kwargs).run()
    assert len(again["repeats"]) == 2
    for task in ("coverage", "path", "state", "output"):
        assert len(_read_task_logs(str(tmp_path / "res"), task)) == 2, task


def test_resume_ignores_journal_from_a_different_slice(tmp_path, monkeypatch):
    """A journal written with max_items=1 must not satisfy a max_items=2
    resume — mixed-shape logs would crash or corrupt the consistency step."""
    base = dict(dataset="humaneval", repeats=1, mock=True, progress=False,
                run_consistency=False, results_dir=str(tmp_path))
    FleetRunner(max_items=1, **base).run()
    result = FleetRunner(max_items=2, resume=True, **base).run()
    assert len(result["repeats"]) == 1
    for task in ("coverage", "path", "state", "output"):
        # identity mismatch → chunk re-ran → a second log exists
        assert len(_read_task_logs(str(tmp_path), task)) == 2, task


# ---------------------------------------------------------------------------
# Sandbox status accounting (ground-truth failures degrade, not crash)
# ---------------------------------------------------------------------------

def test_sandbox_timeout_degrades_and_is_counted(tmp_path, monkeypatch):
    """A *partial* sandbox failure (near-timeout jitter) skips those pairs
    and surfaces the count — the run keeps going."""
    from reval_tpu.dynamics.sandbox import Sandbox
    from reval_tpu.dynamics.states import ExecutionTrace
    from reval_tpu.tasks import TASKS

    orig_run = Sandbox.run
    calls = {"n": 0}

    def flaky_run(self, *args, **kwargs):
        calls["n"] += 1
        if calls["n"] % 2 == 0:
            self.status = "timed out"
            return None, ExecutionTrace()
        return orig_run(self, *args, **kwargs)

    monkeypatch.setattr(Sandbox, "run", flaky_run)
    task = TASKS["coverage"](prompt_type="direct", dataset="humaneval",
                             mock=True, progress=False, max_items=2,
                             results_dir=str(tmp_path))
    metrics = task.run()     # must complete, not assert
    assert task.sandbox_stats["timed out"] >= 1
    assert task.sandbox_stats["ok"] >= 1
    assert metrics["sandbox_errors"]["timed_out"] == task.sandbox_stats["timed out"]
    assert metrics["total"] > 0              # surviving pairs still scored


def test_all_sandboxes_failing_is_fatal(tmp_path, monkeypatch):
    """Every pair failing is a broken host/config, not degradation —
    refuse to score (and journal) an empty run."""
    from reval_tpu.dynamics.sandbox import Sandbox
    from reval_tpu.dynamics.states import ExecutionTrace
    from reval_tpu.tasks import TASKS

    def timed_out_run(self, *args, **kwargs):
        self.status = "timed out"
        return None, ExecutionTrace()

    monkeypatch.setattr(Sandbox, "run", timed_out_run)
    task = TASKS["coverage"](prompt_type="direct", dataset="humaneval",
                             mock=True, progress=False, max_items=1,
                             results_dir=str(tmp_path))
    with pytest.raises(RuntimeError, match="all .* pairs"):
        task.run()


def test_sandbox_stats_absent_on_clean_runs(tmp_path):
    from reval_tpu.tasks import TASKS

    task = TASKS["coverage"](prompt_type="direct", dataset="humaneval",
                             mock=True, progress=False, max_items=1,
                             results_dir=str(tmp_path))
    metrics = task.run()
    assert "sandbox_errors" not in metrics   # reference trailer unchanged
    assert task.sandbox_stats["ok"] > 0
    assert task.sandbox_stats["timed out"] == 0


def test_consistency_tolerates_degraded_pairs():
    """A pair whose sandbox degraded in one task's planning but not
    another's (near-timeout jitter) must score wrong, not desynchronise
    the ladder and crash a finished fleet at its final step."""
    from reval_tpu.tasks.consistency import ConsistencyScorer

    scorer = object.__new__(ConsistencyScorer)
    scorer.progress = False
    trailer = {"acc": 0.0}

    def rows(atomics):
        return [{"generation": [{"results": atomics}]}, trailer]

    scorer.logs = {
        "coverage": rows([{"response": True, "expected": True}] * 2),
        "state": rows([]),                       # degraded: sandbox skipped
        "path": rows([{"response": [3], "expected": [7]}] * 2),
        "output": rows([{"pass": False}]),
    }
    # each aligned case: c=True, s=False (degraded), p=False, o=False → 0.125
    assert scorer.run() == 12.5


def test_infer_failures_surface_in_trailer(tmp_path):
    from reval_tpu.tasks import TASKS

    class Sentinel(EchoBackend):
        info = "mock_model_direct"

        def infer_many(self, prompts):
            out = ["[ANSWER]YES[/ANSWER]"] * len(prompts)
            out[0] = INFER_FAILED
            return out

    task = TASKS["coverage"](model=Sentinel(), prompt_type="direct",
                             dataset="humaneval", mock=True, progress=False,
                             max_items=1, results_dir=str(tmp_path))
    metrics = task.run()
    assert metrics["infer_failures"] == 1
    assert metrics["total"] > 0              # the slot still scored (wrong)


# ---------------------------------------------------------------------------
# Server handshake over real HTTP
# ---------------------------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_healthz_route():
    from reval_tpu.serving import EngineServer

    srv = EngineServer(lambda prompts, **kw: list(prompts), model_id="hm",
                       port=0).start()
    try:
        for route in ("/healthz", "/v1/healthz"):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{route}", timeout=10) as resp:
                assert resp.status == 200
                assert json.load(resp) == {"status": "ok", "model": "hm"}
    finally:
        srv.shutdown()


def test_client_constructed_before_server_waits_for_handshake():
    """The launcher race: client first, server seconds later — the client
    must block on the handshake instead of dying with URLError."""
    from reval_tpu.inference.client import HTTPClientBackend
    from reval_tpu.serving import EngineServer

    port = _free_port()
    started = []

    def boot():
        time.sleep(0.3)
        srv = EngineServer(lambda prompts, **kw: ["late"] * len(prompts),
                           model_id="late-model", port=port).start()
        started.append(srv)

    threading.Thread(target=boot, daemon=True).start()
    try:
        client = HTTPClientBackend(model_id="local", port=port, temp=0.0,
                                   prompt_type="direct", wait_for_server_s=15)
        assert client._server_model == "late-model"
        assert client.infer_one("hi") == "late"
    finally:
        for srv in started:
            srv.shutdown()


def test_client_gives_up_when_no_server_appears():
    from reval_tpu.inference.client import HTTPClientBackend

    port = _free_port()
    with pytest.raises(TimeoutError, match="not reachable"):
        HTTPClientBackend(model_id="m", port=port, temp=0.0,
                          prompt_type="direct", wait_for_server_s=0.2)


# ---------------------------------------------------------------------------
# CLI chaos smoke target (the tier-1 regression canary for this layer)
# ---------------------------------------------------------------------------

def test_chaos_rejects_multihost_global(capsys):
    """No retry layer can wrap pod-collective inference, so injected
    faults would abort the pod unretried — the CLI refuses up front."""
    from reval_tpu.cli import main

    assert main(["fleet", "--mock", "--chaos", "0.3",
                 "--multihost", "global"]) == 1
    assert "incompatible" in capsys.readouterr().out


def test_chaos_smoke_cli(tmp_path, capsys):
    from reval_tpu.cli import main

    argv = ["fleet", "--mock", "--chaos", "0.3", "--resume",
            "--max-items", "1", "--repeats", "2",
            "--set", f"results_dir={tmp_path}",
            "--set", 'retry={"base_delay": 0.001, "jitter": 0.0}',
            "--set", "progress=false"]
    assert main(list(argv)) == 0
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["lost_prompts"] == 0
    assert summary["consistency"] is not None
    ckpt = tmp_path / FleetCheckpoint.FILENAME
    assert ckpt.exists()
    assert len(ckpt.read_text().splitlines()) == 8   # 2 repeats × 4 tasks

    # second invocation resumes a finished run: no chunk re-runs, no new logs
    assert main(list(argv)) == 0
    for task in ("coverage", "path", "state", "output"):
        d = tmp_path / f"{task}@mock_model_direct"
        assert len(list(d.iterdir())) == 2, task
