"""HFTokenizer prompt-token parity with transformers/vLLM semantics.

vLLM's completions server tokenises the raw prompt with the checkpoint
tokenizer's default special-token behaviour (``add_special_tokens=True``:
a llama-style tokenizer prepends exactly one BOS, a gpt2-style one adds
nothing) — reference inference.py:115-131 sends prompts to exactly that
path.  ``HFTokenizer.encode`` must match it token for token: a silent
double-BOS (or missing BOS) shifts every downstream logit (VERDICT round
2, weak item 6)."""

import pytest
from transformers import AutoTokenizer

from reval_tpu.inference.tpu.tokenizer import HFTokenizer

PROMPTS = [
    "def add(a, b):\n    return a + b",
    "[PYTHON]\nx = 1\n[/PYTHON]",
    "",
    " leading space",
]


def _char_vocab():
    chars = [chr(i) for i in range(32, 127)] + ["\n", "\t"]
    vocab = {c: i for i, c in enumerate(chars)}
    for special in ("<unk>", "<s>", "</s>"):
        vocab[special] = len(vocab)
    return vocab


@pytest.fixture(scope="module")
def llama_style(tmp_path_factory):
    """BOS-prepending tokenizer (llama semantics: one <s> per encode)."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers
    from tokenizers.processors import TemplateProcessing
    from transformers import PreTrainedTokenizerFast

    path = tmp_path_factory.mktemp("tok") / "llama-style"
    vocab = _char_vocab()
    tok = Tokenizer(models.BPE(vocab=vocab, merges=[], unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Split("", "isolated")
    tok.decoder = decoders.Fuse()
    tok.post_processor = TemplateProcessing(
        single="<s> $A", pair="<s> $A <s> $B",
        special_tokens=[("<s>", vocab["<s>"])])
    path.mkdir(parents=True)
    tok.save(str(path / "tokenizer.json"))
    fast = PreTrainedTokenizerFast(
        tokenizer_file=str(path / "tokenizer.json"),
        bos_token="<s>", eos_token="</s>", unk_token="<unk>")
    fast.save_pretrained(path)
    return str(path)


@pytest.fixture(scope="module")
def bosless(tmp_path_factory):
    """gpt2-style tokenizer: no special tokens added on encode."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast

    path = tmp_path_factory.mktemp("tok") / "bosless"
    vocab = _char_vocab()
    tok = Tokenizer(models.BPE(vocab=vocab, merges=[], unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Split("", "isolated")
    tok.decoder = decoders.Fuse()
    path.mkdir(parents=True)
    tok.save(str(path / "tokenizer.json"))
    fast = PreTrainedTokenizerFast(
        tokenizer_file=str(path / "tokenizer.json"),
        eos_token="</s>", unk_token="<unk>")
    fast.save_pretrained(path)
    return str(path)


def test_llama_style_prepends_exactly_one_bos(llama_style):
    ours = HFTokenizer(llama_style)
    ref = AutoTokenizer.from_pretrained(llama_style)
    bos = ref.bos_token_id
    for prompt in PROMPTS:
        got = ours.encode(prompt)
        want = ref.encode(prompt, add_special_tokens=True)
        assert got == want, (prompt, got, want)
        assert got[0] == bos
        assert got.count(bos) == 1, f"double BOS for {prompt!r}: {got}"


def test_bosless_adds_no_specials(bosless):
    ours = HFTokenizer(bosless)
    ref = AutoTokenizer.from_pretrained(bosless)
    specials = set(ref.all_special_ids)
    for prompt in PROMPTS:
        got = ours.encode(prompt)
        assert got == ref.encode(prompt, add_special_tokens=True)
        assert got == ref.encode(prompt, add_special_tokens=False)
        assert not (set(got) & specials), (prompt, got)


def test_decode_strips_specials_roundtrip(llama_style):
    ours = HFTokenizer(llama_style)
    for prompt in PROMPTS:
        ids = ours.encode(prompt)
        assert ours.decode(ids) == prompt
        # generation path: decode(prompt ids + eos) must not leak "</s>"
        assert ours.decode(ids + [ours.eos_id]) == prompt


def test_pad_falls_back_to_eos(llama_style):
    ours = HFTokenizer(llama_style)
    assert ours.pad_id == ours.eos_id    # no pad token in the checkpoint
