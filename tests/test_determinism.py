"""Determinism observatory: the tier-1 parity gate + matrix engine tests.

The fast tier runs the REAL parity slice once (the CLI with
``--cells <PARITY_SLICE>`` — xla vs both Pallas kernels, paged vs
static, dp2 vs dp1, batch width) and pins:

- the slice is CLEAN at HEAD (a kernel PR that perturbs greedy outputs
  turns this red with a named cell + first divergent token);
- the artifact round-trips its schema, the detmatrix lint pass accepts
  it (and bites on a vanished cell), and ``tools/obs_report.py`` reads
  it;
- the ``reval_determinism_*`` telemetry renders through the existing
  Prometheus/snapshot machinery;
- an injected logit perturbation (``REVAL_TPU_DETERMINISM_PERTURB``) is
  caught with correct first-divergent-token attribution.

Unit tests (no engines) cover diff attribution, discovery skip reasons,
and per-cell failure degradation.
"""

from __future__ import annotations

import copy
import glob
import importlib.util
import json
import os

import pytest

from reval_tpu.obs import metrics as obs_metrics
from reval_tpu.obs.determinism import (BENCH_SLICE, PARITY_SLICE, PROBES,
                                       SCHEMA, CellSpec, _MatrixRunner,
                                       default_cells, diff_tokens,
                                       discover_cells, gate_failures,
                                       record_matrix, reference_fingerprint,
                                       render_table, run_matrix,
                                       validate_matrix)
from reval_tpu.obs.metrics import MetricsRegistry, parse_prometheus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name: str):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"{name}_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# units — no engines
# ---------------------------------------------------------------------------

class TestUnits:
    def test_taxonomy_names_unique_and_reference_present(self):
        cells = default_cells()
        names = [c.name for c in cells]
        assert len(names) == len(set(names))
        from reval_tpu.obs.determinism import DEFAULT_REFERENCE

        assert DEFAULT_REFERENCE in names
        assert set(PARITY_SLICE) <= set(names)
        assert set(BENCH_SLICE) <= set(names)
        # the parity slice is exactly the bit-identical contract cells
        for c in cells:
            if c.name in PARITY_SLICE:
                assert c.expect == "bit_identical", c.name

    def test_diff_tokens_earliest_token_index_wins_across_probes(self):
        ref = [[1, 2, 3, 4], [5, 6, 7, 8]]
        got = [[1, 2, 3, 9], [5, 6, 0, 8]]     # probe0 @3, probe1 @2
        first = diff_tokens(ref, got)
        assert first == {"probe": 1, "token": 2, "ref": 7, "got": 0}

    def test_diff_tokens_handles_length_mismatch_and_equality(self):
        assert diff_tokens([[1, 2]], [[1, 2]]) is None
        first = diff_tokens([[1, 2, 3]], [[1, 2]])
        assert first == {"probe": 0, "token": 2, "ref": 3, "got": None}

    def test_discovery_skips_oversized_dp_with_reason(self):
        specs = default_cells() + [
            CellSpec("paged-xla-fp32-dp99-b2", "dp_paged", "xla", dp=99)]
        avail, skipped = discover_cells(specs)
        assert "paged-xla-fp32-dp99-b2" in skipped
        assert "devices" in skipped["paged-xla-fp32-dp99-b2"]
        assert all(s.name != "paged-xla-fp32-dp99-b2" for s in avail)

    def test_run_cell_degrades_build_failure_to_skip_with_reason(self,
                                                                 monkeypatch):
        """A broken backend is a report finding, never a crash."""
        runner = _MatrixRunner(PROBES, 4, "")

        def boom(spec):
            raise RuntimeError("backend exploded on load")

        monkeypatch.setattr(runner, "_build", boom)
        row = runner.run_cell(CellSpec("paged-xla-fp32-b2", "paged", "xla"),
                              topk=4)
        assert row["status"] == "skipped"
        assert "backend exploded on load" in row["reason"]
        assert row["axes"]["engine"] == "paged"


# ---------------------------------------------------------------------------
# the tier-1 parity slice — ONE real run shared by the module
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def parity_cli(tmp_path_factory):
    """Run the CLI over the parity slice once; share (rc, artifact)."""
    out = str(tmp_path_factory.mktemp("detmatrix"))
    tool = _load_tool("determinism_matrix")
    rc = tool.main(["--tiny", "--cells", ",".join(PARITY_SLICE),
                    "--out", out,
                    "--table", os.path.join(out, "table.md")])
    arts = sorted(glob.glob(os.path.join(out, "determinism-*.json")))
    assert arts, "CLI wrote no matrix artifact"
    with open(arts[0]) as f:
        matrix = json.load(f)
    return rc, out, arts[0], matrix


class TestParityGateAtHead:
    def test_cli_exits_clean_and_covers_the_slice(self, parity_cli):
        rc, _, _, matrix = parity_cli
        assert rc == 0, matrix["summary"]["gate_failures"]
        assert matrix["summary"]["cells_run"] >= 6
        assert matrix["summary"]["gate_failures"] == []

    def test_every_parity_cell_is_bit_identical_at_head(self, parity_cli):
        """THE gate: xla vs pallas vs pallas_seq kernels, paged vs
        static engines, dp2 vs dp1, slot width — all greedy-identical."""
        _, _, _, matrix = parity_cli
        for name in PARITY_SLICE:
            row = matrix["cells"][name]
            if name == matrix["reference"]:
                assert row["status"] == "ref"
                continue
            assert row["status"] == "agree", (
                f"{name}: {row.get('diff', row.get('reason'))}")
            assert row["diff"]["tokens_equal"]
            assert row["diff"]["topk_ids_equal"]
            assert row["diff"]["answers_equal"]

    def test_unselected_cells_are_skipped_with_reason_never_dropped(
            self, parity_cli):
        _, _, _, matrix = parity_cli
        assert set(matrix["cells"]) == {c.name for c in default_cells()}
        for name, row in matrix["cells"].items():
            if row["status"] == "skipped":
                assert row["reason"], name

    def test_rendered_table_names_every_cell(self, parity_cli):
        _, out, _, matrix = parity_cli
        with open(os.path.join(out, "table.md")) as f:
            table = f.read()
        for name in matrix["cells"]:
            assert f"`{name}`" in table
        assert "REFERENCE" in table


class TestArtifactSchema:
    def test_schema_validates_and_round_trips(self, parity_cli):
        _, _, path, matrix = parity_cli
        assert matrix["schema"] == SCHEMA
        assert validate_matrix(matrix) == []
        # byte round trip through disk preserved validity
        assert validate_matrix(json.loads(json.dumps(matrix))) == []
        assert reference_fingerprint(matrix)

    def test_validate_bites_on_vanished_cell_and_reasonless_skip(
            self, parity_cli):
        _, _, _, matrix = parity_cli
        broken = copy.deepcopy(matrix)
        del broken["cells"]["static-fp32-b2"]
        errs = validate_matrix(broken)
        assert any("static-fp32-b2" in e and "absent" in e for e in errs)

        broken = copy.deepcopy(matrix)
        skipped = next(n for n, r in broken["cells"].items()
                       if r["status"] == "skipped")
        broken["cells"][skipped].pop("reason")
        assert any("without a reason" in e for e in validate_matrix(broken))

        assert validate_matrix({"schema": "bogus"})[0].startswith("schema")

    def test_detmatrix_lint_pass_accepts_head_and_bites(self, parity_cli,
                                                        tmp_path):
        from reval_tpu.analysis.detmatrix import run as lint_run

        _, _, path, matrix = parity_cli
        root = str(tmp_path)
        os.makedirs(os.path.join(root, "tpu_watch"))
        good = os.path.join(root, "tpu_watch", "determinism-good.json")
        with open(good, "w") as f:
            json.dump(matrix, f)
        assert lint_run({}, root) == []

        broken = copy.deepcopy(matrix)
        del broken["cells"][sorted(broken["cells"])[0]]
        with open(os.path.join(root, "tpu_watch",
                               "determinism-zbad.json"), "w") as f:
            json.dump(broken, f)
        msgs = [str(v) for v in lint_run({}, root)]
        assert any("absent from the report" in m for m in msgs)
        assert all("determinism-good" not in m for m in msgs)

        # a truncated artifact is a violation, not a silent skip
        with open(os.path.join(root, "tpu_watch",
                               "determinism-zbad.json"), "w") as f:
            f.write('{"schema": "reval-det')
        assert any("unreadable" in str(v) for v in lint_run({}, root))

    def test_obs_report_reads_the_artifact(self, parity_cli, capsys):
        """The matrix embeds a registry snapshot under "metrics" — the
        existing snapshot renderer reads it unmodified."""
        tool = _load_tool("obs_report")
        _, _, path, _ = parity_cli
        snap = tool.load_snapshot(path)
        out = tool.render(snap, "matrix")
        assert obs_metrics.DET_CELLS in out
        assert obs_metrics.DET_DRIFT in out


class TestTelemetry:
    def test_record_matrix_feeds_declared_metrics(self, parity_cli):
        _, _, _, matrix = parity_cli
        reg = MetricsRegistry()
        record_matrix(matrix, reg)
        s = matrix["summary"]
        assert reg.counter(obs_metrics.DET_CELLS).value == s["cells_run"]
        assert reg.counter(obs_metrics.DET_AGREE).value == s["cells_agree"]
        assert (reg.counter(obs_metrics.DET_DIVERGED).value
                == s["cells_diverged"])
        assert (reg.counter(obs_metrics.DET_SKIPPED).value
                == s["cells_skipped"])
        # one drift observation per compared cell
        n_compared = sum(1 for r in matrix["cells"].values() if "diff" in r)
        assert reg.histogram(obs_metrics.DET_DRIFT).count == n_compared

    def test_determinism_metrics_render_through_prometheus(self, parity_cli):
        """Surfacing contract: the registry the matrix feeds renders on
        the same exposition path /metrics uses, and the grammar checker
        accepts it — any server/router merge therefore exposes it."""
        _, _, _, matrix = parity_cli
        reg = MetricsRegistry()
        record_matrix(matrix, reg)
        text = reg.render_prometheus()
        samples = parse_prometheus(text)
        assert samples[obs_metrics.DET_CELLS] == matrix["summary"]["cells_run"]
        assert f"{obs_metrics.DET_DRIFT}_count" in samples
        assert samples[obs_metrics.DET_DEPTH] == -1.0  # clean slice

    def test_snapshot_in_artifact_matches_summary(self, parity_cli):
        _, _, _, matrix = parity_cli
        counters = matrix["metrics"]["counters"]
        assert (counters[obs_metrics.DET_CELLS]
                == matrix["summary"]["cells_run"])


# ---------------------------------------------------------------------------
# injected perturbation — the gate must trip with correct attribution
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def perturbed_matrix():
    """Perturb the static cell's lm_head and run ref + static only."""
    target = "static-fp32-b2"
    os.environ["REVAL_TPU_DETERMINISM_PERTURB"] = target
    try:
        matrix = run_matrix(select=[target])
    finally:
        os.environ.pop("REVAL_TPU_DETERMINISM_PERTURB", None)
    return target, matrix


class TestInjectedPerturbation:
    def test_gate_fails_loudly_naming_cell_and_first_token(
            self, perturbed_matrix):
        target, matrix = perturbed_matrix
        row = matrix["cells"][target]
        assert row["status"] == "diverged"
        failures = matrix["summary"]["gate_failures"]
        assert failures, "perturbed bit-identical cell must fail the gate"
        assert any(target in msg and "probe" in msg and "token" in msg
                   for msg in failures)
        # gate_failures() recomputes identically from the artifact
        assert gate_failures(matrix) == failures

    def test_first_divergence_attribution_is_correct(self, perturbed_matrix):
        """The named (probe, token) really is the earliest mismatch of
        the recorded streams — recomputed independently here."""
        target, matrix = perturbed_matrix
        ref_tokens = matrix["cells"][matrix["reference"]]["tokens"]
        got_tokens = matrix["cells"][target]["tokens"]
        first = matrix["cells"][target]["diff"]["first_divergence"]
        assert first == diff_tokens(ref_tokens, got_tokens)
        probe, tok = first["probe"], first["token"]
        assert ref_tokens[probe][:tok] == got_tokens[probe][:tok]
        assert ref_tokens[probe][tok] != got_tokens[probe][tok]
        assert matrix["summary"]["divergence_depth"] == tok

    def test_perturbation_moves_logit_drift_histogram(self, perturbed_matrix):
        target, matrix = perturbed_matrix
        drift = matrix["cells"][target]["diff"]["logit_drift"]
        assert drift > 1.0     # the boost is ~8 on one column
        assert matrix["summary"]["cells_diverged"] >= 1
        assert render_table(matrix).count("PARITY GATE FAILURES") == 1
        # traceability: the artifact records WHICH cell was perturbed
        assert matrix["perturb"] == target


# ---------------------------------------------------------------------------
# obs_report --determinism: cross-round drift detection
# ---------------------------------------------------------------------------

class TestObsReportDeterminismMode:
    def _round(self, tmp_path, name, fp, diverged=0, block=True,
               perturb=None):
        obj = {"metric": "m", "value": 1.0}
        if block:
            obj["determinism"] = {
                "reference": "paged-xla-fp32-b2", "fingerprint": fp,
                "probes_digest": "d", "cells_run": 3,
                "cells_diverged": diverged, "gate_failures": [],
                "perturb": perturb}
        path = os.path.join(str(tmp_path), name)
        with open(path, "w") as f:
            json.dump(obj, f)
        return path

    def test_names_first_round_whose_fingerprint_changed(self, tmp_path,
                                                         capsys):
        tool = _load_tool("obs_report")
        paths = [self._round(tmp_path, "BENCH_r01.json", "aaaa"),
                 self._round(tmp_path, "BENCH_r02.json", "aaaa"),
                 self._round(tmp_path, "BENCH_r03.json", None, block=False),
                 self._round(tmp_path, "BENCH_r04.json", "bbbb", diverged=2),
                 self._round(tmp_path, "BENCH_r05.json", "bbbb")]
        rc = tool.main(["--determinism", *paths])
        out = capsys.readouterr().out
        assert rc == 0
        assert "first drift: BENCH_r04.json" in out
        assert "was aaaa in BENCH_r02.json" in out
        assert "no determinism block" in out          # r03 named, not hidden
        assert out.count("fingerprint CHANGED") == 1  # r05 matches r04

    def test_no_drift_reads_clean(self, tmp_path, capsys):
        tool = _load_tool("obs_report")
        paths = [self._round(tmp_path, "BENCH_r01.json", "cccc"),
                 self._round(tmp_path, "BENCH_r02.json", "cccc")]
        rc = tool.main(["--determinism", *paths])
        assert rc == 0
        assert "no fingerprint drift" in capsys.readouterr().out

    def test_stray_non_object_json_degrades_to_one_row(self, tmp_path,
                                                       capsys):
        """A globbed-in array/string artifact must cost one unreadable
        row, never the whole report."""
        tool = _load_tool("obs_report")
        stray = os.path.join(str(tmp_path), "stray.json")
        with open(stray, "w") as f:
            f.write("[1, 2, 3]")
        paths = [self._round(tmp_path, "BENCH_r01.json", "cccc"), stray,
                 self._round(tmp_path, "BENCH_r02.json", "cccc")]
        rc = tool.main(["--determinism", *paths])
        out = capsys.readouterr().out
        assert rc == 0
        assert "unreadable" in out
        assert "no fingerprint drift" in out    # the cccc rows survive

    def test_perturbed_round_is_flagged_not_phantom_drift(self, tmp_path,
                                                          capsys):
        """A leftover REVAL_TPU_DETERMINISM_PERTURB run must be visibly
        marked in drift history, or its fingerprint change reads as a
        phantom cross-commit numerics change."""
        tool = _load_tool("obs_report")
        paths = [self._round(tmp_path, "BENCH_r01.json", "cccc"),
                 self._round(tmp_path, "BENCH_r02.json", "dddd",
                             perturb="static-fp32-b2")]
        rc = tool.main(["--determinism", *paths])
        out = capsys.readouterr().out
        assert rc == 0
        assert "PERTURBED: static-fp32-b2" in out

    def test_reads_raw_matrix_artifacts_too(self, parity_cli, capsys):
        tool = _load_tool("obs_report")
        _, _, path, matrix = parity_cli
        rc = tool.main(["--determinism", path])
        out = capsys.readouterr().out
        assert rc == 0
        assert reference_fingerprint(matrix) in out
