"""Dataset loaders, ClassEval hooks, prompting — incl. reference-data fixtures."""

import pytest

from reval_tpu.datasets import DREvalDataset, Families, family_of, resolve_split
from reval_tpu.dynamics import CodeSpace, Sandbox
from reval_tpu.datasets.dreval import ClassEvalHooks
from reval_tpu.prompting import STOP_STRING, build_direct_prompt, build_cot_prompt


class TestConstants:
    def test_family_ranges(self):
        assert family_of(0) == "humaneval"
        assert family_of(84) == "humaneval"
        assert family_of(85) == "classeval"
        assert family_of(154) == "mbpp"
        assert family_of(655) == "mathqa"
        with pytest.raises(ValueError):
            family_of(9999)

    def test_resolve_split(self):
        data, tasks = resolve_split("humaneval")
        assert data.name == "DREval_data.jsonl"
        data, tasks = resolve_split("mbpp")
        assert "black" in data.name
        data, tasks = resolve_split("mbpp", "mbpp_raw")
        assert data.name == "DREval_data_mbpp.jsonl"


class TestLoading:
    @pytest.fixture(scope="class")
    def main_ds(self):
        return DREvalDataset.load("humaneval")

    def test_indexed_access(self, main_ds):
        assert main_ds.entry_point(0) == "has_close_elements"
        assert "def has_close_elements" in main_ds.code(0)
        assert isinstance(main_ds.inputs(0), list)

    def test_task_iteration_filters_by_family(self, main_ds):
        idxs = [int(r["idx"]) for r in main_ds.iter_tasks("humaneval")]
        assert idxs and all(i <= Families.HUMANEVAL_END for i in idxs)
        c_idxs = [int(r["idx"]) for r in main_ds.iter_tasks("classeval")]
        assert c_idxs and all(Families.CLASSEVAL_START <= i <= Families.CLASSEVAL_END for i in c_idxs)


class TestDatasetFixtures:
    """Reference test.py's dataset-driven sandbox checks (test_sandbox_2/5)."""

    def test_humaneval_idx5_trace(self):
        ds = DREvalDataset.load("humaneval")
        space = CodeSpace()
        fn = space.load_function(ds.entry_point(5), ds.code(5))
        result, states = Sandbox(fn).run([1, 2, 3, 4])
        assert result == (10, 24)
        assert 0 in states.get_local(14, "sum_value")
        assert 6 in states.get_local(15, "sum_value")
        assert 6 in states.get_local(16, "prod_value")

    def test_classeval_idx85_trace(self):
        import inspect

        ds = DREvalDataset.load("classeval")
        idx = 85
        space = CodeSpace()
        space.load_class(ds.entry_point(idx), ds.code(idx))
        classes = space.load_test_classes(
            ds.entry_point(idx),
            ds.code(idx),
            ds.test_code(idx),
            ClassEvalHooks.name_pattern,
            ClassEvalHooks.validation,
            ClassEvalHooks.postprocess,
        )
        # NOTE: the reference's test_sandbox_5 expectations target upstream
        # ClassEval ordering; in this snapshot idx 85 is AreaCalculator
        # (reference test.py:100-119 would fail here).  Assert the same
        # *kinds* of facts against the actual data.
        assert len(classes) >= 1
        tcls = classes[0]
        obj = tcls()
        sandbox = Sandbox(obj.dreval_test)
        _, states = sandbox.run()
        assert sandbox.status == "ok"
        # __init__ body: line 6 = `self.radius = radius`
        assert states.get_coverage(6)
        # pre-execution snapshot at line 6 holds the ctor argument
        assert 2 in [s.get_local("radius") for s in states.states_before(6)]
        # after-semantics: self.radius is set once line 6 has run
        assert 2 in states.get_attr(6, "self", "radius")
        assert 2 in states.interpret_var(6, "self.radius")
        # calculate_circle_area body: line 9 returns pi * r**2
        assert states.get_coverage(9)
        assert abs(states.get_return(9) - 12.566370614359172) < 1e-9
        assert inspect.isroutine(states.get_attr(6, "self", "calculate_circle_area")[0])
        assert -1 in states.get_next_line(9) or 9 in states.trace


class TestPrompting:
    def test_direct_coverage_prompt_renders(self):
        p = build_direct_prompt(
            "coverage",
            code="def f(x):\n    return x",
            invocation="f(1)",
            invocation_abbr="f(1)",
            line=2,
            codeline="    return x",
        )
        assert p.endswith("[ANSWER]")
        assert "Is Line 2 (    return x) executed when f(1) is called?" in p
        assert STOP_STRING == "[/ANSWER]"

    def test_all_eight_templates_render(self):
        import string

        from reval_tpu.prompting import build_prompt, template_path

        supplied = dict(
            code="def f():\n    pass",
            invocation="f()",
            invocation_abbr="f()",
            line=1,
            codeline="def f():",
            var="x",
        )
        for task in ("coverage", "path", "state", "output"):
            for style in ("direct", "cot"):
                template = template_path(task, style).read_text()
                needed = {f for _, f, _, _ in string.Formatter().parse(template) if f}
                assert needed <= set(supplied), f"{task}/{style} needs unknown fields {needed}"
                rendered = build_prompt(task, style, **{k: supplied[k] for k in needed})
                assert len(rendered) > 100
