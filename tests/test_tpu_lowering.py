"""Chip-free TPU lowering checks for every Pallas kernel.

Interpret-mode tests (test_pallas_attention.py etc.) validate kernel
*numerics* but never exercise the Pallas→Mosaic lowering pass, so a
kernel can be bit-exact on CPU and still die on its first real-chip
compile — the round-3 seq kernel did exactly that (``.at[].set`` on a
loop-carried array lowers to ``scatter``, which Mosaic's TPU lowering
rejects; found only when the tunnel came back in round 4).

``jax.export`` with ``platforms=["tpu"]`` runs that lowering pass on any
host: the Mosaic primitive-support layer that threw on the chip throws
here too (verified: the round-3 seq kernel fails this test with the
same error).  Every Pallas kernel must have a case here for each
structurally distinct configuration (dtype, quantized scales, GQA vs
MHA, window/softcap) — shapes can be small; lowering cares about
structure, not size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from reval_tpu.ops.pallas_attention import (
    paged_decode_attention_pallas,
    paged_decode_attention_pallas_seq,
)

B, P, NPAGES, SPAN, D = 4, 128, 24, 6, 128

KERNELS = [paged_decode_attention_pallas, paged_decode_attention_pallas_seq]


def _export_tpu(fn, *args):
    jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def _operands(h, h_kv, store_dtype=jnp.bfloat16):
    q = jnp.zeros((B, h, D), jnp.bfloat16)
    kp = jnp.zeros((NPAGES * P, h_kv, D), store_dtype)
    bt = jnp.zeros((B, SPAN), jnp.int32)
    sl = jnp.ones((B,), jnp.int32)
    return q, kp, bt, sl


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("h,h_kv", [(16, 16), (16, 4), (8, 1)])
def test_lowers_bf16(kernel, h, h_kv):
    q, kp, bt, sl = _operands(h, h_kv)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=P)

    _export_tpu(f, q, kp, kp, bt, sl)


@pytest.mark.parametrize("kernel", KERNELS)
def test_lowers_int8_pool(kernel):
    q, kp, bt, sl = _operands(16, 16, jnp.int8)
    scales = jnp.ones((NPAGES * P, 16), jnp.float32)

    def f(q, kp, vp, bt, sl, ks, vs):
        return kernel(q, kp, vp, bt, sl, page_size=P, k_scales=ks, v_scales=vs)

    _export_tpu(f, q, kp, kp, bt, sl, scales, scales)


@pytest.mark.parametrize("kernel", KERNELS)
def test_lowers_window_softcap(kernel):
    q, kp, bt, sl = _operands(16, 4)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=P, window=256, softcap=30.0)

    _export_tpu(f, q, kp, kp, bt, sl)
