"""Chip-free TPU lowering checks for every Pallas kernel.

Interpret-mode tests (test_pallas_attention.py etc.) validate kernel
*numerics* but never exercise the Pallas→Mosaic lowering pass, so a
kernel can be bit-exact on CPU and still die on its first real-chip
compile — the round-3 seq kernel did exactly that (``.at[].set`` on a
loop-carried array lowers to ``scatter``, which Mosaic's TPU lowering
rejects; found only when the tunnel came back in round 4).

``jax.export`` with ``platforms=["tpu"]`` runs that lowering pass on any
host: the Mosaic primitive-support layer that threw on the chip throws
here too (verified: the round-3 seq kernel fails this test with the
same error).  Every Pallas kernel must have a case here for each
structurally distinct configuration (dtype, quantized scales, GQA vs
MHA, window/softcap) — shapes can be small; lowering cares about
structure, not size.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import pytest

# jax 0.4.x does not re-export the submodule lazily: `jax.export` is an
# AttributeError until someone imports it explicitly.  Probe it here and
# SKIP (never fail) when this host's jax cannot run the lowering pass at
# all — a skip names the environment gap; a failure must mean a kernel
# regression.
try:
    import jax.export  # noqa: F401
    _EXPORT_SKIP = None
except ImportError as _e:  # pragma: no cover — depends on host jax build
    _EXPORT_SKIP = f"jax.export unavailable on this host ({_e})"

pytestmark = pytest.mark.skipif(_EXPORT_SKIP is not None,
                                reason=_EXPORT_SKIP or "")

from reval_tpu.ops.pallas_attention import (
    paged_decode_attention_pallas,
    paged_decode_attention_pallas_seq,
)

B, P, NPAGES, SPAN, D = 4, 128, 24, 6, 128


@functools.lru_cache(maxsize=None)
def _kernel_lowering_skip() -> str | None:
    """Capability canary for the DIRECT kernel exports — THE shared
    probe (``reval_tpu.inference.tpu.aot_cache.kernel_export_skip``):
    both decode kernels transpose a K/V page in VMEM, and older jax
    builds' Mosaic TPU lowering has no rule for that (1, 0, 2)
    transpose — the chip's jax does.  One definition serves both
    consumers: these kernel-level tests skip with the environment named,
    and the AOT executable cache reports ``aot.unsupported`` (counted,
    logged, degraded to a fresh compile) instead of raising a doomed
    export per variant.  If the canary passes, a kernel-test failure is
    a real regression.  The whole-program exports below don't take this
    skip: they lower today and must keep lowering.

    Cached + called from test bodies (not at import), so collection and
    deselected runs never pay the multi-second canary export."""
    if _EXPORT_SKIP is not None:    # module already skipped wholesale
        return _EXPORT_SKIP
    from reval_tpu.inference.tpu.aot_cache import kernel_export_skip

    return kernel_export_skip()


@pytest.fixture()
def kernel_exports_supported():
    reason = _kernel_lowering_skip()
    if reason is not None:
        pytest.skip(reason)

KERNELS = [paged_decode_attention_pallas, paged_decode_attention_pallas_seq]


def _export_tpu(fn, *args):
    jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def _operands(h, h_kv, store_dtype=jnp.bfloat16):
    q = jnp.zeros((B, h, D), jnp.bfloat16)
    kp = jnp.zeros((NPAGES * P, h_kv, D), store_dtype)
    bt = jnp.zeros((B, SPAN), jnp.int32)
    sl = jnp.ones((B,), jnp.int32)
    return q, kp, bt, sl


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("h,h_kv", [(16, 16), (16, 4), (8, 1)])
def test_lowers_bf16(kernel_exports_supported, kernel, h, h_kv):
    q, kp, bt, sl = _operands(h, h_kv)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=P)

    _export_tpu(f, q, kp, kp, bt, sl)


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("h,h_kv", [(16, 16), (16, 4)])   # MHA + GQA folding
def test_lowers_int8_pool(kernel_exports_supported, kernel, h, h_kv):
    q, kp, bt, sl = _operands(h, h_kv, jnp.int8)
    scales = jnp.ones((NPAGES * P, h_kv), jnp.float32)

    def f(q, kp, vp, bt, sl, ks, vs):
        return kernel(q, kp, vp, bt, sl, page_size=P, k_scales=ks, v_scales=vs)

    _export_tpu(f, q, kp, kp, bt, sl, scales, scales)


@pytest.mark.parametrize("kernel", KERNELS)
def test_lowers_window_softcap(kernel_exports_supported, kernel):
    q, kp, bt, sl = _operands(16, 4)

    def f(q, kp, vp, bt, sl):
        return kernel(q, kp, vp, bt, sl, page_size=P, window=256, softcap=30.0)

    _export_tpu(f, q, kp, kp, bt, sl)


# -- whole-program lowering ---------------------------------------------------
# The kernels above are necessary but not sufficient: the engine's jitted
# programs wrap them in scans, scatters (KV page writes), quantization,
# and sampling — any of which can hit its own Mosaic/XLA-TPU gap.  Export
# the REAL chunk programs at tiny shapes with the Pallas kernel forced on.

@pytest.fixture()
def tiny_engine_parts():
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.models import ModelConfig, init_random_params
    from reval_tpu.models.paged import init_paged_cache

    cfg = ModelConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32)
    params = init_random_params(cfg, seed=0, dtype="bfloat16")
    return PagedTPUEngine, init_paged_cache, cfg, params


@pytest.mark.parametrize("kv_dtype,backend", [
    ("", "pallas"), ("", "pallas_seq"),
    ("int8", "pallas"), ("int8", "pallas_seq"),
])
@pytest.mark.parametrize("filtered", [False, True])
def test_decode_chunk_program_lowers(tiny_engine_parts, monkeypatch,
                                     kv_dtype, backend, filtered):
    PagedTPUEngine, init_paged_cache, cfg, params = tiny_engine_parts
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", backend)
    cache = init_paged_cache(cfg, num_pages=20, page_size=16,
                             dtype=jnp.bfloat16, kv_dtype=kv_dtype)
    span, b = 6, 4
    state = jnp.zeros((b, span + 6), jnp.int32).at[:, span].set(1)
    sampling = jnp.zeros((b, 3), jnp.float32)
    fn = partial(PagedTPUEngine._decode_chunk, cfg=cfg, steps=4,
                 filtered=filtered)
    _export_tpu(fn, params, state, cache, sampling, None)


def test_table_patch_program_lowers():
    """The chunk pipeline's in-place table patch (a dynamic-update-slice
    over the packed state's table columns) must lower for TPU: it chains
    directly onto the decode chunk's output on the hot path.  Exports
    the engine's REAL function, not a reconstruction."""
    from reval_tpu.inference.tpu.paged_engine import patch_state_tables

    span, b = 6, 4
    state = jnp.zeros((b, span + 6), jnp.int32)
    tables = jnp.zeros((b, span), jnp.int32)
    _export_tpu(patch_state_tables, state, tables)
