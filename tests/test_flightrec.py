"""Flight recorder, structured logs, postmortem bundles, watch console
(fast tier — host-only, no jit, no TPU).

Covers the ISSUE-5 contracts: the bounded per-step ring (wrap, ordering,
O(µs) record cost, the REVAL_TPU_FLIGHTREC=0 A/B), the structured-log
event layer (declared namespace, bounded ring, JSON-line emission),
postmortem production on every trigger (watchdog trip, driver fault,
deadline storm, SIGUSR1-style on-demand), bundle completeness (flight
runway covering the stalled step, in-flight request table, readiness),
`tools/postmortem_report.py` rendering, `GET /debugz` under concurrent
scrape, writer retention/rate-limit/atomicity, and the `reval_tpu watch`
console against a live mock server.
"""

import glob
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from reval_tpu.obs.flightrec import (
    FIELDS,
    FlightRecorder,
    PostmortemWriter,
    build_bundle,
)
from reval_tpu.obs.logging import EVENTS, log_event, recent
from reval_tpu.serving import ContinuousSession, EngineServer, MockStepEngine

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")

RESPONSE = "mock_model_gen"


def make_stack(tmp_path, *, step_s=0.0, tokens_per_step=16, watchdog_s=30.0,
               step_chaos=None, response=RESPONSE):
    eng = MockStepEngine(response=response, step_s=step_s,
                         tokens_per_step=tokens_per_step)
    session = ContinuousSession(eng, watchdog_s=watchdog_s,
                                step_chaos=step_chaos,
                                postmortem_dir=str(tmp_path))
    srv = EngineServer(session.generate_fn(), model_id="flightrec-mock",
                       port=0, serialize=False, max_tokens_cap=8000)
    srv.attach_session(session)
    return eng, session, srv.start()


def bundles_in(tmp_path) -> list[str]:
    return sorted(glob.glob(os.path.join(str(tmp_path),
                                         "postmortem-*.json")))


def wait_for_bundles(tmp_path, n=1, timeout=5.0) -> list[str]:
    """The dump runs on the tripping thread AFTER handles resolve —
    callers that woke on result() must wait for the file."""
    deadline = time.monotonic() + timeout
    while len(bundles_in(tmp_path)) < n and time.monotonic() < deadline:
        time.sleep(0.02)
    return bundles_in(tmp_path)


# ---------------------------------------------------------------------------
# the ring itself
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_wraps_and_keeps_newest(self):
        fr = FlightRecorder(capacity=8, enabled=True)
        for i in range(20):
            fr.record(i, 0, 100 - i, 0, 0, 0, 0, 0, 32, 0.001, 0.0, (i,))
        assert fr.total == 20
        recs = fr.records()
        assert len(recs) == 8
        assert [r[0] for r in recs] == list(range(12, 20))  # newest 8, ordered
        snap = fr.snapshot(last=3)
        assert [s["step"] for s in snap] == [17, 18, 19]
        assert set(snap[0]) == set(FIELDS)
        assert snap[-1]["running"] == 19
        assert snap[-1]["seq_ids"] == [19]

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("REVAL_TPU_FLIGHTREC", "0")
        fr = FlightRecorder(capacity=8)
        assert fr.enabled is False
        fr.record(1, 0, 0, 0, 0, 0, 0, 0, 0, 0.0, 0.0, ())
        assert fr.total == 0 and fr.records() == []

    def test_record_cost_stays_sub_20us(self):
        """The <2% hot-path bar (PERF.md) rests on a record being one
        tuple store; a generous ceiling catches an accidental O(n) or
        formatting regression without flaking on slow CI."""
        fr = FlightRecorder()
        n = 20_000
        ids = (1, 2, 3, 4)
        t0 = time.perf_counter()
        for i in range(n):
            fr.record(4, 2, 100, 8, 4, 0, 1024, 0, 32, 0.001, 0.0005, ids)
        per = (time.perf_counter() - t0) / n
        assert per < 20e-6, f"record() cost {per * 1e6:.2f}µs"
        assert fr.total == n

    def test_partial_snapshot_before_wrap(self):
        fr = FlightRecorder(capacity=16, enabled=True)
        fr.record(1, 0, 0, 0, 0, 0, 0, 0, 0, 0.002, 0.0, ())
        snap = fr.snapshot()
        assert len(snap) == 1 and snap[0]["step"] == 0
        assert snap[0]["step_ms"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# structured logging
# ---------------------------------------------------------------------------

class TestStructuredLog:
    def test_event_record_shape_and_ring(self):
        rec = log_event("session.postmortem", request_id="req-1",
                        path="/tmp/x.json", reason="test")
        assert rec["component"] == "session"
        assert rec["event"] == "session.postmortem"
        assert rec["request_id"] == "req-1"
        assert rec["fields"] == {"path": "/tmp/x.json", "reason": "test"}
        assert recent(1)[-1] == rec
        # the line is one JSON object
        assert json.loads(json.dumps(rec, default=str))["event"] \
            == "session.postmortem"

    def test_unknown_event_never_raises(self):
        # a typo in an except block must not mask the real error — the
        # static lint (tools/check_metrics.py) is the enforcement
        rec = log_event("engine.deadlock", level="error")
        assert rec["level"] == "error"

    def test_min_level_filter_and_bound(self):
        log_event("client.wait", level="debug", target="t", timeout_s=1)
        log_event("session.driver_error", level="error")
        errs = recent(min_level="error")
        assert errs and all(e["level"] == "error" for e in errs)

    def test_every_declared_event_has_component_prefix(self):
        for name in EVENTS:
            comp, _, rest = name.partition(".")
            assert comp and rest, name


# ---------------------------------------------------------------------------
# postmortem triggers through the real session/server stack
# ---------------------------------------------------------------------------

class _StallAt:
    """step_chaos stand-in: a deterministic stall at one exact session
    step (EngineStepChaos's schedule is seeded-random; the acceptance
    test wants runway BEFORE the stall)."""

    def __init__(self, at: int, stall_s: float):
        self.at, self.stall_s, self.n = at, stall_s, 0
        self.injected = []

    def tick(self) -> None:
        self.n += 1
        if self.n == self.at:
            self.injected.append(("stall", self.n))
            time.sleep(self.stall_s)


def test_watchdog_trip_dumps_bundle_covering_the_stall(tmp_path):
    """THE acceptance path: a stalled step trips the watchdog, the
    postmortem bundle's flight records cover the runway into the stall
    (the stalled request rides the newest record), and
    tools/postmortem_report.py renders it without error."""
    from reval_tpu.serving import EngineWedged

    chaos = _StallAt(at=12, stall_s=2.0)
    # construct with a generous watchdog (the driver's FIRST enqueue
    # lazily imports the paged engine — jax — which a 0.2s watchdog
    # would misread as a wedge), warm up, then tighten it
    eng, session, srv = make_stack(tmp_path, tokens_per_step=1,
                                   watchdog_s=30.0, step_chaos=chaos)
    try:
        assert session.submit(["w"], max_new_tokens=2).result(timeout=10)
        warm_ticks = eng.flightrec.total
        assert 0 < warm_ticks < 12      # runway left before the stall
        session.watchdog_s = 0.2
        handle = session.submit(["x"], max_new_tokens=64)
        with pytest.raises(EngineWedged):
            handle.result(timeout=15)
        assert eng.stats.watchdog_trips == 1
        paths = wait_for_bundles(tmp_path)
        assert len(paths) == 1
        bundle = json.loads(open(paths[0]).read())
        assert bundle["reason"] == "watchdog_trip"
        assert "no progress" in bundle["error"]
        # the runway covers every tick up to the one the engine stalled
        # in: contiguous step ordinals ending at the recorder's head
        flight = bundle["flight"]
        assert len(flight) == eng.flightrec.total >= warm_ticks + 1
        assert [r["step"] for r in flight] == list(range(len(flight)))
        # the stalled request is ON the newest record and in the table
        stalled = [r for r in bundle["requests"] if not r["done"]]
        assert len(stalled) == 1
        assert stalled[0]["seq_id"] in flight[-1]["seq_ids"]
        assert stalled[0]["generated_tokens"] >= 1   # mid-decode
        # the in-flight submission table names the stranded handle
        assert len(bundle["inflight"]) == 1
        assert bundle["readiness"]["wedged"] is True
        assert bundle["metrics"]["counters"][
            "reval_serving_watchdog_trips_total"] == 1
        assert any(e["event"] == "session.watchdog_trip"
                   for e in bundle["recent_logs"])
        assert bundle["fingerprint"]["pid"] == os.getpid()
    finally:
        srv.shutdown()

    # render the human timeline — must exit 0 and show the story
    sys.path.insert(0, TOOLS)
    try:
        import postmortem_report
        assert postmortem_report.main([paths[0]]) == 0
        text = postmortem_report.render(bundle)
    finally:
        sys.path.remove(TOOLS)
    assert "watchdog_trip" in text
    assert "flight records" in text
    assert "step" in text and "hb_ms" in text
    assert "in-flight submissions: 1" in text


def test_driver_exception_dumps_bundle(tmp_path):
    from reval_tpu.resilience import EngineStepChaos

    chaos = EngineStepChaos(rate=1.0, modes=("error",), max_faults=1)
    eng, session, srv = make_stack(tmp_path, step_chaos=chaos)
    try:
        with pytest.raises(RuntimeError):
            session.submit(["x"], max_new_tokens=8).result(timeout=10)
        paths = wait_for_bundles(tmp_path)
        assert len(paths) == 1
        bundle = json.loads(open(paths[0]).read())
        assert bundle["reason"] == "driver_exception"
        assert "chaos" in bundle["error"]
        # the driver recovers: the next request serves normally
        out = session.submit(["y"], max_new_tokens=32).result(timeout=10)
        assert out == [RESPONSE]
    finally:
        srv.shutdown()


def test_deadline_storm_dumps_bundle_lone_expiry_does_not(tmp_path):
    from reval_tpu.serving import DeadlineExceeded

    eng, session, srv = make_stack(tmp_path, step_s=0.05, tokens_per_step=1)
    try:
        # one expiry: routine, no bundle
        h = session.submit(["a"], max_new_tokens=64, deadline_s=0.01)
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=10)
        deadline = time.monotonic() + 5
        while session._driver_reqs and time.monotonic() < deadline:
            time.sleep(0.01)
        assert bundles_in(tmp_path) == []
        # a storm (>= DEADLINE_STORM_N in one sweep): bundle
        handles = [session.submit([f"p{i}"], max_new_tokens=64,
                                  deadline_s=0.01) for i in range(4)]
        for h in handles:
            with pytest.raises(DeadlineExceeded):
                h.result(timeout=10)
        deadline = time.monotonic() + 5
        while not bundles_in(tmp_path) and time.monotonic() < deadline:
            time.sleep(0.02)
        paths = bundles_in(tmp_path)
        assert len(paths) == 1
        bundle = json.loads(open(paths[0]).read())
        assert bundle["reason"] == "deadline_storm"
        assert eng.stats.deadline_expired == 5
    finally:
        srv.shutdown()


def test_on_demand_dump_and_debugz_route(tmp_path):
    """server.dump_postmortem (the SIGUSR1/SIGTERM hook) writes a live
    bundle; /debugz serves the same document without writing."""
    eng, session, srv = make_stack(tmp_path)
    try:
        session.submit(["x"], max_new_tokens=32).result(timeout=10)
        path = srv.dump_postmortem("sigusr1")
        assert path is not None and os.path.exists(path)
        bundle = json.loads(open(path).read())
        assert bundle["reason"] == "sigusr1"
        assert bundle["model"] == "flightrec-mock"
        assert bundle["flight"], "served requests must leave flight records"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debugz", timeout=10) as r:
            live = json.loads(r.read())
        assert live["reason"] == "debugz"
        assert live["readiness"]["ready"] is True
        assert live["flight"][-1]["step"] == bundle["flight"][-1]["step"]
        assert bundles_in(tmp_path) == [path]   # /debugz wrote nothing new
    finally:
        srv.shutdown()


def test_debugz_wellformed_under_concurrent_scrape(tmp_path):
    eng, session, srv = make_stack(tmp_path, step_s=0.002, tokens_per_step=2)
    bad: list[str] = []
    stop = threading.Event()

    def scrape():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}/debugz",
                        timeout=10) as r:
                    bundle = json.loads(r.read())
                if bundle.get("reason") != "debugz":
                    bad.append("wrong reason")
            except Exception as exc:  # noqa: BLE001
                bad.append(repr(exc))

    def post(i):
        try:
            session.submit([f"p{i}"], max_new_tokens=48).result(timeout=30)
        except Exception as exc:  # noqa: BLE001
            bad.append(f"post {i}: {exc!r}")

    scrapers = [threading.Thread(target=scrape, daemon=True)
                for _ in range(4)]
    posts = [threading.Thread(target=post, args=(i,)) for i in range(8)]
    try:
        for t in scrapers + posts:
            t.start()
        for t in posts:
            t.join(timeout=60)
    finally:
        stop.set()
        for t in scrapers:
            t.join(timeout=10)
        srv.shutdown()
    assert bad == []


def test_multisession_bundle_has_one_section_per_replica(tmp_path):
    from reval_tpu.serving import MultiSession

    engines = [MockStepEngine(), MockStepEngine()]
    ms = MultiSession(engines, postmortem_dir=str(tmp_path))
    try:
        ms.submit(["x"], max_new_tokens=16).result(timeout=10)
        bundle = ms.postmortem_bundle("debugz")
        assert len(bundle["replicas"]) == 2
        assert all(rep["reason"] == "debugz" and "readiness" in rep
                   for rep in bundle["replicas"])
        # the process-global envelope (fingerprint, log ring) appears
        # ONCE, on the outer bundle — not once per replica
        assert "fingerprint" in bundle and "recent_logs" in bundle
        assert all("fingerprint" not in rep and "recent_logs" not in rep
                   for rep in bundle["replicas"])
        json.dumps(bundle)      # wire-safe end to end
        # server-level dumps (SIGUSR1/SIGTERM) honor the configured dir
        from reval_tpu.serving import EngineServer

        srv = EngineServer(ms.generate_fn(), model_id="dp", port=0,
                           serialize=False, max_tokens_cap=8000)
        srv.attach_session(ms)
        path = srv.dump_postmortem("sigusr1")
        assert path is not None and path.startswith(str(tmp_path))
    finally:
        ms.close()


# ---------------------------------------------------------------------------
# writer semantics
# ---------------------------------------------------------------------------

class TestPostmortemWriter:
    def test_retention_prunes_oldest(self, tmp_path):
        w = PostmortemWriter(str(tmp_path), keep=3, min_interval_s=0.0)
        written = [w.dump(build_bundle(f"r{i}")) for i in range(6)]
        assert all(written)
        left = bundles_in(tmp_path)
        assert len(left) == 3
        reasons = [json.loads(open(p).read())["reason"] for p in left]
        assert reasons == ["r3", "r4", "r5"]

    def test_rate_limit_is_per_reason(self, tmp_path):
        """A storm of one trigger collapses; a DIFFERENT trigger landing
        inside the window still writes (a sigterm_drain right after a
        driver_exception must not vanish)."""
        w = PostmortemWriter(str(tmp_path), min_interval_s=60.0)
        assert w.dump(build_bundle("driver_exception")) is not None
        assert w.dump(build_bundle("driver_exception")) is None
        assert w.dump(build_bundle("sigterm_drain")) is not None
        assert len(bundles_in(tmp_path)) == 2

    def test_failed_write_does_not_arm_the_rate_limit(self, tmp_path):
        w = PostmortemWriter(str(tmp_path), min_interval_s=60.0)
        w.directory = str(tmp_path / "file")
        (tmp_path / "file").write_text("x")     # unwritable: a FILE
        assert w.dump(build_bundle("r")) is None
        w.directory = str(tmp_path)             # "disk recovered"
        assert w.dump(build_bundle("r")) is not None

    def test_unwritable_dir_never_raises(self, tmp_path):
        victim = tmp_path / "file"
        victim.write_text("x")           # a FILE where a dir must be
        w = PostmortemWriter(str(victim), min_interval_s=0.0)
        assert w.dump(build_bundle("r")) is None

    def test_no_tmp_droppings(self, tmp_path):
        w = PostmortemWriter(str(tmp_path), min_interval_s=0.0)
        w.dump(build_bundle("r"))
        assert not glob.glob(os.path.join(str(tmp_path), "*.tmp"))


# ---------------------------------------------------------------------------
# the watch console
# ---------------------------------------------------------------------------

class TestWatchConsole:
    def test_watch_renders_live_server(self, tmp_path, capsys):
        from reval_tpu.watch import run_watch

        eng, session, srv = make_stack(tmp_path)
        try:
            for i in range(3):
                session.submit([f"p{i}"], max_new_tokens=32).result(timeout=10)
            rc = run_watch(["--port", str(srv.port), "--interval", "0.01",
                            "--iterations", "2", "--no-clear"])
        finally:
            srv.shutdown()
        assert rc == 0
        out = capsys.readouterr().out
        assert "reval_tpu watch" in out and "READY" in out
        assert "throughput" in out and "req/s" in out
        assert "latency" in out and "p50" in out
        assert "page pool" in out and "lifecycle" in out
        assert "last faults" in out
        # second refresh computes real rates from counter deltas
        assert out.count("reval_tpu watch") == 2

    def test_watch_survives_unreachable_server(self, capsys):
        import socket

        from reval_tpu.watch import run_watch

        with socket.socket() as s:      # grab a port nobody serves
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        rc = run_watch(["--port", str(port), "--interval", "0.01",
                        "--iterations", "2", "--no-clear"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "UNREACHABLE" in out and "retrying" in out

    def test_render_screen_canned(self):
        """Unit render: dp bundle shape, fault tail, rate deltas."""
        from reval_tpu.obs import metrics as m
        from reval_tpu.watch import render_screen

        reg = m.MetricsRegistry()
        reg.counter(m.REQUESTS).add(20)
        reg.counter("reval_engine_generated_tokens_total").add(400)
        reg.gauge(m.QUEUED_TOKENS).set(128)
        reg.gauge(m.FREE_PAGES).set(55)
        for v in (0.01, 0.02, 0.4):
            reg.histogram(m.TTFT).observe(v)
            reg.histogram(m.E2E).observe(v * 2)
        status = {"model": "m", "draining": False,
                  "metrics": reg.snapshot(), "readiness": {"ready": True}}
        debug = {"replicas": [{"flight": [
            {"step": 7, "running": 3, "queued": 1, "free_pages": 55,
             "cached_pages": 9, "pinned_pages": 2, "step_ms": 1.25}]}],
            "recent_logs": [{"ts": "t", "level": "error",
                             "event": "session.driver_error",
                             "error": "boom"}]}
        prev = {m.REQUESTS: 10}
        screen = render_screen(status, debug, prev, 2.0, "h:1")
        assert "req/s 5.0" in screen
        assert "queued_tokens 128" in screen
        assert "free 55" in screen and "cached 9" in screen
        assert "session.driver_error" in screen
        assert "p50" in screen


# ---------------------------------------------------------------------------
# the A/B: recorder off end to end
# ---------------------------------------------------------------------------

def test_flightrec_disabled_serves_with_empty_flight(tmp_path, monkeypatch):
    monkeypatch.setenv("REVAL_TPU_FLIGHTREC", "0")
    eng, session, srv = make_stack(tmp_path)
    try:
        out = session.submit(["x"], max_new_tokens=32).result(timeout=10)
        assert out == [RESPONSE]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debugz", timeout=10) as r:
            bundle = json.loads(r.read())
        assert bundle["flight"] == []       # off, and the bundle says so
        assert bundle["readiness"]["ready"] is True
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# the real paged engine feeds the same ring
# ---------------------------------------------------------------------------

def test_paged_engine_drive_tick_feeds_recorder():
    """Not just the mock: the real engine's drive tick records slots,
    queue, page pool, and chunk sizes every step."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params

    cfg = ModelConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16)
    params = init_random_params(cfg, seed=0, dtype="float32")
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                         page_size=128, max_seq_len=256)
    try:
        eng.generate(["def f(x):", "def g(y):"], max_new_tokens=8,
                     temperature=0.0)
        assert eng.flightrec.total >= 1
        recs = eng.flightrec.snapshot()
        assert [r["step"] for r in recs] == list(range(len(recs)))
        # the pool gauge is live (tiny engine: 1 + slots*pages_per_seq)
        assert all(r["free_pages"] > 0 for r in recs)
        assert any(r["running"] > 0 for r in recs)
        assert all(r["step_ms"] >= 0 for r in recs)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# the events lint actually bites
# ---------------------------------------------------------------------------

def test_check_metrics_catches_undeclared_event(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import check_metrics

        root = tmp_path / "repo"
        (root / "reval_tpu" / "obs").mkdir(parents=True)
        (root / "reval_tpu" / "rogue.py").write_text(
            'log_event("engine.made_up_event", level="error")\n')
        readme = ["| `reval_requests_total` | c | x |"]
        readme += [f"| `{name}` | {help} |" for name, help in
                   check_metrics._events_spec().items()]
        (root / "README.md").write_text("\n".join(readme) + "\n")
        errors = check_metrics.run_checks(str(root))
    finally:
        sys.path.remove(TOOLS)
    assert any("engine.made_up_event" in e and "not declared" in e
               for e in errors)
    # declared-but-never-emitted is also reported (both directions)
    assert any("never emitted" in e for e in errors)
