"""Ragged paged attention + true continuous batching (PR-17).

Three layers under test:

- the **kernel**: ``ragged_paged_attention_pallas`` (interpret mode) and
  the ``ragged_paged_attention_xla`` gather reference against an
  independent per-row numpy oracle, across every ragged shape the engine
  dispatches — pure decode, pure prefill, mixed waves, verify windows,
  single rows, page-straddling contexts, padding rows/columns;
- the **engine**: ``_tick_ragged`` greedy output must be bit-identical
  to the incumbent split-dispatch engine, speculative verify included,
  and a long prefill must admit mid-decode without stalling the rows
  already decoding (the continuous-batching drill);
- the **contract**: one jit dispatch per drive tick (tier-1 — asserted
  via the ``paged.ragged_step`` call counter against ``ragged_ticks``),
  and a second boot under the AOT executable cache paying zero fresh
  compiles on the ragged entry.

Cache layout matches models/paged.py: token-major flat pool
``[N * P, H_kv, D]``; page ``n`` is rows ``[n * P, (n + 1) * P)``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from reval_tpu.ops.pallas_attention import (
    ragged_paged_attention_pallas,
    ragged_paged_attention_xla,
)

PAGE = 128


def ragged_reference(q, k_pages, v_pages, tables, ctx_lens, q_lens, *,
                     page_size=PAGE, window=None, softcap=None):
    """Independent oracle: per-(row, column, head) dense attention in
    f64 numpy.  Column ``j`` of row ``b`` attends kv positions
    ``< ctx_lens[b] + j + 1``; padding columns are returned as zeros
    (the caller compares valid columns only)."""
    q = np.asarray(q, np.float64)
    b, w, h, d = q.shape
    h_kv = k_pages.shape[1]
    g = h // h_kv
    scale = d ** -0.5
    kp = np.asarray(k_pages, np.float64).reshape(-1, page_size, h_kv, d)
    vp = np.asarray(v_pages, np.float64).reshape(-1, page_size, h_kv, d)
    tables = np.asarray(tables)
    out = np.zeros_like(q)
    for bi in range(b):
        s_max = tables.shape[1] * page_size
        k_seq = kp[tables[bi]].reshape(s_max, h_kv, d)
        v_seq = vp[tables[bi]].reshape(s_max, h_kv, d)
        for j in range(int(q_lens[bi])):
            alen = int(ctx_lens[bi]) + j + 1
            lo = max(0, alen - window) if window is not None else 0
            for hh in range(h):
                kvh = hh // g
                s = k_seq[lo:alen, kvh] @ q[bi, j, hh] * scale
                if softcap is not None:
                    s = softcap * np.tanh(s / softcap)
                p = np.exp(s - s.max())
                p /= p.sum()
                out[bi, j, hh] = p @ v_seq[lo:alen, kvh]
    return out


def make_wave(ctx_lens, q_lens, *, w=4, h=4, h_kv=2, d=128, max_pages=3,
              seed=0, dtype=jnp.float32):
    """Random q + pool for one ragged wave with the given descriptors.
    Distinct per-row page ids so a wrong table lookup changes numbers."""
    b = len(ctx_lens)
    rng = np.random.default_rng(seed)
    n_pages = 1 + b * max_pages
    q = jnp.asarray(rng.standard_normal((b, w, h, d)), dtype)
    kp = jnp.asarray(rng.standard_normal((n_pages * PAGE, h_kv, d)), dtype)
    vp = jnp.asarray(rng.standard_normal((n_pages * PAGE, h_kv, d)), dtype)
    perm = rng.permutation(np.arange(1, n_pages))
    tables = jnp.asarray(perm[: b * max_pages].reshape(b, max_pages),
                         jnp.int32)
    return (q, kp, vp, tables, jnp.asarray(ctx_lens, jnp.int32),
            jnp.asarray(q_lens, jnp.int32))


# every ragged shape the engine dispatches, by (ctx_lens, q_lens):
WAVES = {
    # all rows a single query over a real context
    "pure-decode": ([57, 130, 1, 250], [1, 1, 1, 1]),
    # all rows prefill-from-zero windows of varying width
    "pure-prefill": ([0, 0, 0], [4, 1, 3]),
    # one wave mixing decode, prefill, spec-verify, and a feed window
    "mixed": ([57, 0, 40, 130], [1, 4, 3, 4]),
    # draft-verify windows mid-sequence (q_len = 1 + ndraft)
    "verify-window": ([33, 97, 260], [3, 4, 2]),
    "single-row": ([PAGE * 2 - 2], [4]),
    # contexts at/around page edges; windows straddling a boundary
    "page-straddle": ([PAGE - 1, PAGE, PAGE + 1, PAGE * 2 - 2],
                      [4, 4, 4, 4]),
    # idle/padding row (ctx 0, one masked-to-first-token query) riding
    # next to real work
    "padding-rows": ([0, 200, 0], [1, 1, 4]),
}


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(WAVES))
@pytest.mark.parametrize("dot_mode", ["swap", "wide"])
def test_ragged_kernel_matches_oracle(name, dot_mode):
    ctx, ql = WAVES[name]
    q, kp, vp, tables, ctx_lens, q_lens = make_wave(ctx, ql)
    ref = ragged_reference(q, kp, vp, tables, ctx_lens, q_lens)
    xla = ragged_paged_attention_xla(q, kp, vp, tables, ctx_lens, q_lens,
                                     page_size=PAGE)
    pal = ragged_paged_attention_pallas(q, kp, vp, tables, ctx_lens,
                                        q_lens, page_size=PAGE,
                                        interpret=True, dot_mode=dot_mode)
    for b, n in enumerate(np.asarray(q_lens)):
        np.testing.assert_allclose(np.asarray(xla)[b, :n], ref[b, :n],
                                   rtol=1e-4, atol=1e-4, err_msg=name)
        np.testing.assert_allclose(np.asarray(pal)[b, :n], ref[b, :n],
                                   rtol=1e-4, atol=1e-4, err_msg=name)
    # padding columns are unspecified but must stay finite (never NaN —
    # a downstream reduction over the full rectangle would poison it)
    assert np.isfinite(np.asarray(pal)).all()
    assert np.isfinite(np.asarray(xla)).all()


@pytest.mark.slow
def test_ragged_kernel_gqa_and_mha_groupings():
    ctx, ql = WAVES["mixed"]
    for h, h_kv in ((4, 4), (8, 2)):        # G == 1 and G == 4
        q, kp, vp, tables, cl, qls = make_wave(ctx, ql, h=h, h_kv=h_kv,
                                               seed=h)
        ref = ragged_reference(q, kp, vp, tables, cl, qls)
        pal = ragged_paged_attention_pallas(q, kp, vp, tables, cl, qls,
                                            page_size=PAGE, interpret=True)
        for b, n in enumerate(np.asarray(qls)):
            np.testing.assert_allclose(np.asarray(pal)[b, :n], ref[b, :n],
                                       rtol=1e-4, atol=1e-4)


@pytest.mark.slow
@pytest.mark.parametrize("window,softcap", [(64, None), (None, 30.0),
                                            (64, 30.0)])
def test_ragged_kernel_window_and_softcap(window, softcap):
    ctx, ql = WAVES["mixed"]
    q, kp, vp, tables, cl, qls = make_wave(ctx, ql, seed=7)
    ref = ragged_reference(q, kp, vp, tables, cl, qls, window=window,
                           softcap=softcap)
    xla = ragged_paged_attention_xla(q, kp, vp, tables, cl, qls,
                                     page_size=PAGE, window=window,
                                     softcap=softcap)
    pal = ragged_paged_attention_pallas(q, kp, vp, tables, cl, qls,
                                        page_size=PAGE, interpret=True,
                                        window=window, softcap=softcap)
    for b, n in enumerate(np.asarray(qls)):
        np.testing.assert_allclose(np.asarray(xla)[b, :n], ref[b, :n],
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(pal)[b, :n], ref[b, :n],
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_ragged_kernel_int8_pool():
    ctx, ql = WAVES["mixed"]
    q, kp, vp, tables, cl, qls = make_wave(ctx, ql, seed=11)
    rng = np.random.default_rng(11)
    n_tok, h_kv, _ = kp.shape
    ks = jnp.asarray(rng.uniform(0.01, 0.1, (n_tok, h_kv)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.1, (n_tok, h_kv)), jnp.float32)
    kq = jnp.asarray(rng.integers(-127, 128, kp.shape), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, vp.shape), jnp.int8)
    deq_k = kq.astype(jnp.float32) * ks[..., None]
    deq_v = vq.astype(jnp.float32) * vs[..., None]
    ref = ragged_reference(q, deq_k, deq_v, tables, cl, qls)
    pal = ragged_paged_attention_pallas(q, kq, vq, tables, cl, qls,
                                        page_size=PAGE, interpret=True,
                                        k_scales=ks, v_scales=vs)
    for b, n in enumerate(np.asarray(qls)):
        np.testing.assert_allclose(np.asarray(pal)[b, :n], ref[b, :n],
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_ragged_dead_pages_never_leak():
    """Pages past a row's ragged span are redirected/masked; poisoning
    them must not change any valid output column."""
    ctx, ql = ([40, 3], [2, 1])             # both rows fit in page 0
    q, kp, vp, tables, cl, qls = make_wave(ctx, ql, seed=13)
    base = ragged_paged_attention_pallas(q, kp, vp, tables, cl, qls,
                                         page_size=PAGE, interpret=True)
    poisoned = kp
    for page in np.asarray(tables[:, 1:]).ravel():
        poisoned = poisoned.at[int(page) * PAGE:(int(page) + 1) * PAGE].set(
            1e9)
    out = ragged_paged_attention_pallas(q, poisoned, vp, tables, cl, qls,
                                        page_size=PAGE, interpret=True)
    for b, n in enumerate(np.asarray(qls)):
        np.testing.assert_allclose(np.asarray(out)[b, :n],
                                   np.asarray(base)[b, :n],
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# Engine layer: the ragged drive loop against the incumbent
# ---------------------------------------------------------------------------

PROMPTS = [
    "def add(a, b):\n    return a + b\n\nprint(add(2, 3))",
    "x = 1",
    "for i in range(10):\n    print(i)",
    "def fib(n):\n    return n if n < 2 else fib(n-1) + fib(n-2)",
    "s = 'hello world'\nprint(s.upper())",
]


def tiny_engine(monkeypatch, backend, **kw):
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", backend)
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params

    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 512)
    return PagedTPUEngine(params, cfg, ByteTokenizer(), page_size=128, **kw)


@pytest.mark.slow
def test_ragged_engine_greedy_bit_identical_to_incumbent(monkeypatch):
    """The PR-17 parity contract at engine level: the one-wave ragged
    drive loop emits exactly the incumbent split-dispatch engine's
    greedy stream, mixed admission/preemption effects included."""
    eng = tiny_engine(monkeypatch, "xla", max_slots=3)
    try:
        ref = eng.generate(PROMPTS, max_new_tokens=12, temperature=0.0)
        assert eng.stats.ragged_ticks == 0      # incumbent path ran
    finally:
        eng.close()
    eng = tiny_engine(monkeypatch, "ragged_xla", max_slots=3)
    try:
        out = eng.generate(PROMPTS, max_new_tokens=12, temperature=0.0)
        assert eng.stats.ragged_ticks > 0       # ragged path ran
    finally:
        eng.close()
    assert out == ref


@pytest.mark.slow
def test_ragged_engine_speculative_parity(monkeypatch):
    """Greedy + self-drafting: the ragged verify windows must accept
    exactly what the incumbent accepts — same final streams — while
    actually drafting (repeated prompts feed the n-gram index)."""
    prompts = ["for i in range(10):\n    print(i)"] * 3
    eng = tiny_engine(monkeypatch, "xla", max_slots=3, speculative=True)
    try:
        ref = eng.generate(prompts, max_new_tokens=16, temperature=0.0)
    finally:
        eng.close()
    eng = tiny_engine(monkeypatch, "ragged_xla", max_slots=3,
                      speculative=True)
    try:
        out = eng.generate(prompts, max_new_tokens=16, temperature=0.0)
        assert eng.stats.spec_rounds > 0        # verify windows rode waves
        assert eng.stats.spec_drafted_tokens > 0
    finally:
        eng.close()
    assert out == ref


@pytest.mark.slow
def test_ragged_engine_preemption_parity(monkeypatch):
    """A pool too small for all rows forces preemption mid-stream; the
    ragged loop's reserve/rollback bookkeeping must still land on the
    incumbent's exact greedy output."""
    kw = dict(max_slots=3, num_pages=5, max_seq_len=512)
    eng = tiny_engine(monkeypatch, "xla", **kw)
    try:
        ref = eng.generate(PROMPTS[:4], max_new_tokens=10, temperature=0.0)
    finally:
        eng.close()
    eng = tiny_engine(monkeypatch, "ragged_xla", **kw)
    try:
        out = eng.generate(PROMPTS[:4], max_new_tokens=10, temperature=0.0)
    finally:
        eng.close()
    assert out == ref


@pytest.mark.slow
def test_long_prefill_admits_mid_decode_without_stalling(monkeypatch):
    """The continuous-batching drill: while a long prompt is still
    feeding its prefill windows (RAGGED_FEED shrunk so the feed spans
    many ticks), the row already decoding must keep producing tokens
    EVERY tick — no prefill-wave stall — and both rows must finish with
    the incumbent engine's exact greedy output."""
    import reval_tpu.inference.tpu.paged_engine as pe
    from reval_tpu.inference.tpu.engine import StopScanner
    from reval_tpu.inference.tpu.paged_engine import _Request

    long_prompt = PROMPTS[3] * 6            # ~350 tokens
    short_prompt = PROMPTS[1]
    refs = {}
    eng = tiny_engine(monkeypatch, "xla", max_slots=2)
    try:
        refs[short_prompt] = eng.generate([short_prompt],
                                          max_new_tokens=24,
                                          temperature=0.0)[0]
        refs[long_prompt] = eng.generate([long_prompt], max_new_tokens=24,
                                         temperature=0.0)[0]
    finally:
        eng.close()

    monkeypatch.setattr(pe, "RAGGED_FEED", 32)
    # prefix sharing off: a cached prefix would pre-cover most of the
    # long prompt and collapse the multi-tick feed this drill needs
    eng = tiny_engine(monkeypatch, "ragged_xla", max_slots=2,
                      prefix_sharing=False)
    try:
        def submit(prompt, index):
            ids = eng.encode_clipped(prompt, 24)
            seq_id, node = eng.submit_request(ids, 24)
            return seq_id, _Request(
                index=index, ids=ids, max_new=24,
                scanner=StopScanner(eng.tokenizer, []), temp=0.0,
                key=eng.request_keys(1)[0], node=node)

        st = eng.new_drive_state()
        reqs = {}
        seq_a, req_a = submit(short_prompt, 0)
        reqs[seq_a] = req_a
        while len(req_a.generated) < 4:     # A is decoding steady-state
            eng._drive_tick(reqs, st)

        seq_b, req_b = submit(long_prompt, 1)   # admits mid-decode
        reqs[seq_b] = req_b
        feed_ticks = 0
        # fed_target is stamped AT admission (first tick below), so the
        # loop runs until B's prefill windows are all committed
        while not req_b.done and (req_b.fed_target == 0
                                  or req_b.fed < req_b.fed_target):
            before = len(req_a.generated)
            eng._drive_tick(reqs, st)
            feed_ticks += 1
            if not req_a.done:
                # the drill's point: every feed tick also advanced the
                # decoding row — the long prefill stalled nobody
                assert len(req_a.generated) > before
        assert feed_ticks >= 5              # the feed really spanned ticks
        while any(not r.done for r in reqs.values()):
            eng._drive_tick(reqs, st)
        for seq_id, req in reqs.items():
            eng.release_request(seq_id, req)

        from reval_tpu.inference.tpu.engine import finalize_text
        assert finalize_text(eng.tokenizer, req_a.generated,
                             []) == refs[short_prompt]
        assert finalize_text(eng.tokenizer, req_b.generated,
                             []) == refs[long_prompt]
    finally:
        eng.close()


@pytest.mark.slow
def test_ragged_second_boot_pays_zero_fresh_compiles(tmp_path,
                                                     monkeypatch):
    """Warm-restart economics for the new entry: a second boot under
    the AOT executable cache must deserialize ``paged.ragged_step``
    (ragged_xla is the exportable formulation) instead of compiling —
    zero fresh compiles, bit-identical greedy output."""
    monkeypatch.setenv("REVAL_TPU_AOT_CACHE_DIR", str(tmp_path / "aot"))
    eng = tiny_engine(monkeypatch, "ragged_xla")
    try:
        out1 = eng.generate(PROMPTS[:2], max_new_tokens=8, temperature=0.0)
        aot1 = eng.aot_counters()
        assert aot1["fresh_compiles"] >= 1 and aot1["unsupported"] == 0
    finally:
        eng.close()
    eng = tiny_engine(monkeypatch, "ragged_xla")
    try:
        out2 = eng.generate(PROMPTS[:2], max_new_tokens=8, temperature=0.0)
        assert eng.aot_counters()["fresh_compiles"] == 0
        assert eng.stats.ragged_ticks > 0
    finally:
        eng.close()
    assert out2 == out1


# ---------------------------------------------------------------------------
# The one-dispatch-per-tick contract (tier-1)
# ---------------------------------------------------------------------------

def test_one_dispatch_per_tick_on_mixed_batch(monkeypatch):
    """PR-17's acceptance observable: over a workload that mixes
    still-feeding prefill rows, steady decode rows, and admission
    churn, the ragged engine dispatches EXACTLY one jitted program per
    drive tick (``paged.ragged_step`` calls == ``ragged_ticks``) and
    never touches the split-dispatch programs."""
    import reval_tpu.inference.tpu.paged_engine as pe

    monkeypatch.setattr(pe, "RAGGED_FEED", 16)
    # prefix sharing off: the cache's insert path legitimately runs the
    # prefill program at SUBMIT time, which would blur the per-tick count
    eng = tiny_engine(monkeypatch, "ragged_xla", max_slots=2,
                      prefix_sharing=False)
    try:
        prompts = [PROMPTS[3], PROMPTS[1], PROMPTS[4]]   # feed + decode mix
        eng.generate(prompts, max_new_tokens=6, temperature=0.0)
        calls = eng.jit_counters()["calls"]
        ticks = eng.stats.ragged_ticks
        assert ticks > 0
        assert calls.get("paged.ragged_step", 0) == ticks
        for entry in ("paged.prefill", "paged.prefill_pctx",
                      "paged.commit", "paged.decode_chunk",
                      "paged.verify_chunk"):
            assert calls.get(entry, 0) == 0, entry
        # the wave rectangle is never smaller than the real work in it
        assert eng.stats.ragged_useful_tokens > 0
        assert (eng.stats.ragged_padded_tokens
                >= eng.stats.ragged_useful_tokens)
    finally:
        eng.close()
