"""Scale-realistic numerical fidelity (SURVEY §7 hard part 3; VERDICT
round-2 weak item 4): per-layer drift at FLAGSHIP width/depth.

The tiny-random parity tests (test_models.py) establish implementation
correctness but say nothing about bf16 drift at real scale — error
compounds with width (reduction length) and depth, and the failure mode
that matters is a flipped YES/NO answer at temperature 0.8.  This test
runs the actual deepseek-coder-1.3b shape (24 layers × 2048 hidden,
flagship BASELINE.json configs[0]) with random weights:

1. cross-implementation fp32: our per-layer hidden states vs
   transformers' ``output_hidden_states`` — implementation parity at
   scale, tight tolerance;
2. bf16 vs fp32 (ours): per-layer relative drift with a justified
   bound — bf16 unit roundoff is 2^-8 ≈ 3.9e-3, rounding errors
   accumulate roughly with the square root of the number of sequential
   roundings, so we allow eps * sqrt(ops_per_layer * (l+1)) with
   ops_per_layer ≈ 7 (4 attn matmuls + 3 mlp) and a 4x safety factor;
3. logits-level effect: relative logit error and greedy top-1 agreement
   (reported; asserted only against catastrophic divergence, since
   random-weight logit margins are pessimistically small vs a trained
   model's).

Runs minutes on one CPU core (a 1.3B fp32 torch forward + two jax
forwards); kept as one test function so the cost is paid once.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax
import jax.numpy as jnp

FLAGSHIP = dict(
    vocab_size=32256, hidden_size=2048, intermediate_size=5504,
    num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=16,
    max_position_embeddings=4096, rope_theta=100000.0, rms_norm_eps=1e-6,
    tie_word_embeddings=False,
)

SEQ = 128
BF16_EPS = 2.0 ** -8
OPS_PER_LAYER = 7
SAFETY = 4.0


@pytest.fixture(scope="module")
def flagship_checkpoint(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    path = tmp_path_factory.mktemp("ckpt") / "flagship-random"
    torch.manual_seed(42)
    model = LlamaForCausalLM(LlamaConfig(**FLAGSHIP)).eval()
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def test_flagship_width_bf16_per_layer_fidelity(flagship_checkpoint):
    import torch

    from reval_tpu.models import init_kv_cache, load_checkpoint, prefill

    model, path = flagship_checkpoint
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, FLAGSHIP["vocab_size"] - 1, size=(1, SEQ))

    with torch.no_grad():
        ref = model(torch.tensor(tokens), output_hidden_states=True)
    # hidden_states[l+1] = decoder layer l output (pre final norm)
    ref_hiddens = [h.float().numpy() for h in ref.hidden_states[1:]]
    ref_logits = ref.logits.float().numpy()
    del ref

    params, cfg = load_checkpoint(path, dtype="float32")
    pad = jnp.zeros(1, jnp.int32)
    toks = jnp.asarray(tokens, jnp.int32)

    def run(p, dtype):
        cache = init_kv_cache(cfg, 1, SEQ, dtype=dtype)
        logits, _, hiddens = prefill(p, cfg=cfg, tokens=toks, pad_len=pad,
                                     cache=cache, collect_hiddens=True)
        return (np.asarray(logits, np.float32),
                np.asarray(hiddens, np.float32))

    f32_logits, f32_hiddens = run(params, jnp.float32)

    # -- 1. cross-implementation parity at scale (fp32 vs transformers) --
    # transformers applies the FINAL norm to its last hidden_states entry
    # (LlamaModel.forward norms before appending), so the last layer's
    # pre-norm state isn't observable there — it is covered by the logits
    # check below, which passes through final norm + lm_head.
    for layer, ref_h in enumerate(ref_hiddens[:-1]):
        rel = (np.linalg.norm(f32_hiddens[layer] - ref_h)
               / np.linalg.norm(ref_h))
        assert rel < 2e-3, f"fp32 impl divergence at layer {layer}: {rel:.2e}"
    logit_rel = np.linalg.norm(f32_logits - ref_logits) / np.linalg.norm(ref_logits)
    assert logit_rel < 2e-3, f"fp32 logits diverge: {logit_rel:.2e}"

    # -- 2. bf16 drift, per layer, against the roundoff-growth model ----
    bf16_params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, params)
    bf16_logits, bf16_hiddens = run(bf16_params, jnp.bfloat16)

    drifts = []
    for layer in range(cfg.num_layers):
        rel = (np.linalg.norm(bf16_hiddens[layer] - f32_hiddens[layer])
               / np.linalg.norm(f32_hiddens[layer]))
        bound = SAFETY * BF16_EPS * np.sqrt(OPS_PER_LAYER * (layer + 1))
        drifts.append(rel)
        assert rel < bound, (
            f"bf16 drift at layer {layer}: {rel:.4f} exceeds the "
            f"roundoff-growth bound {bound:.4f} — suggests a bf16-specific "
            f"bug (e.g. a reduction not done in f32), not benign rounding")
    # drift must actually grow like accumulation, not blow up early:
    # the final layer's drift should dominate the first layer's
    assert drifts[-1] > drifts[0]

    # -- 3. logits-level effect ----------------------------------------
    logit_drift = (np.linalg.norm(bf16_logits - f32_logits)
                   / np.linalg.norm(f32_logits))
    agree = float(np.mean(bf16_logits.argmax(-1) == f32_logits.argmax(-1)))
    # random weights are the worst case for argmax stability (near-zero
    # top-1 margins); catastrophic-divergence guard only
    assert logit_drift < 0.10, f"bf16 logit drift {logit_drift:.3f}"
    assert agree > 0.5, f"greedy agreement collapsed: {agree:.2f}"
    print(f"per-layer drift: first={drifts[0]:.4f} last={drifts[-1]:.4f}; "
          f"logits rel-err={logit_drift:.4f}; greedy agreement={agree:.2%}")
