"""Gemma-2 family parity: sandwich norms, logit softcapping, query
scaling, and per-layer alternating sliding/global attention — all four
differ from gemma-1 and silently corrupt logits if ignored.

Oracle: transformers' Gemma2ForCausalLM on a tiny random checkpoint
(fp32, CPU), the same per-family strategy as the other parity suites
(SURVEY §7 hard part 3).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax
import jax.numpy as jnp

TINY_GEMMA2 = dict(
    vocab_size=256, hidden_size=64, intermediate_size=128,
    num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
    head_dim=16, max_position_embeddings=512, rope_theta=10000.0,
    rms_norm_eps=1e-6,
    # window smaller than the test sequence so sliding layers actually mask
    sliding_window=8, query_pre_attn_scalar=32,
    attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
)


def make_hf_gemma2(tmp_path, **overrides):
    import torch
    from transformers import Gemma2Config, Gemma2ForCausalLM

    torch.manual_seed(0)
    cfg = Gemma2Config(**{**TINY_GEMMA2, **overrides})
    model = Gemma2ForCausalLM(cfg).eval()
    # HF inits every RMSNorm weight to zero (identity under the w+1
    # convention) — randomise them so mis-wiring any of the four per-layer
    # norms (input/post-attn/pre-ffw/post-ffw) breaks logits parity
    with torch.no_grad():
        for name, p in model.named_parameters():
            if "norm" in name:
                p.copy_(torch.randn_like(p) * 0.3)
    path = tmp_path / "tiny-gemma2"
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def hf_logits(model, tokens):
    import torch

    with torch.no_grad():
        out = model(torch.tensor(tokens))
    return out.logits.float().numpy()


@pytest.fixture(scope="module")
def gemma2(tmp_path_factory):
    from reval_tpu.models import load_checkpoint

    tmp = tmp_path_factory.mktemp("ckpt")
    model, path = make_hf_gemma2(tmp)
    params, cfg = load_checkpoint(path, dtype="float32")
    return model, params, cfg


class TestGemma2Parity:
    def test_config_parsed(self, gemma2):
        _, _, cfg = gemma2
        assert cfg.use_post_norms and cfg.alt_sliding
        assert cfg.attn_softcap == 50.0 and cfg.final_softcap == 30.0
        assert cfg.query_scale == 32 and cfg.sliding_window == 8
        assert cfg.window_for_layer(0) == 8       # even layers sliding
        assert cfg.window_for_layer(1) is None    # odd layers global

    def test_post_norm_weights_loaded(self, gemma2):
        _, params, _ = gemma2
        layers = params["layers"]
        assert layers["post_attn_norm_w"].shape == (4, 64)
        assert layers["post_mlp_norm_w"].shape == (4, 64)
        # the fixture randomises norms, so the four per-layer norms are
        # distinct — a mis-mapped loader would alias two of them
        assert not np.allclose(np.asarray(layers["mlp_norm_w"]),
                               np.asarray(layers["post_attn_norm_w"]))

    def test_logits_match_hf_past_the_window(self, gemma2):
        from reval_tpu.models import logits_for_tokens

        model, params, cfg = gemma2
        rng = np.random.default_rng(0)
        # t=24 > window=8: sliding layers mask real history; a wrong
        # window rule (or all-global) diverges hard here
        tokens = rng.integers(0, 255, size=(2, 24))
        ours = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        theirs = hf_logits(model, tokens)
        np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-3)

    def test_decode_matches_prefill(self, gemma2):
        from reval_tpu.models import (
            decode_step, init_kv_cache, logits_for_tokens, prefill)

        _, params, cfg = gemma2
        rng = np.random.default_rng(2)
        tokens = rng.integers(0, 255, size=(2, 17))
        full = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        cache = init_kv_cache(cfg, 2, 20, dtype=jnp.float32)
        pad = jnp.zeros(2, jnp.int32)
        _, cache = prefill(params, cfg, jnp.asarray(tokens[:, :-1]), pad, cache)
        logits, _ = decode_step(params, cfg, jnp.asarray(tokens[:, -1:]),
                                pad, cache, jnp.int32(16))
        np.testing.assert_allclose(np.asarray(logits), full[:, -1, :],
                                   atol=3e-4, rtol=3e-3)

    def test_engines_agree(self, gemma2):
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

        _, params, cfg = gemma2
        tok = ByteTokenizer()
        prompts = ["def f(x):\n    return x + 1\n\nassert f(", "x = 1\ny ="]
        eng = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=256)
        want = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
        paged = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=64,
                               max_seq_len=256)
        got = paged.generate(prompts, max_new_tokens=10, temperature=0.0)
        paged.close()
        assert got == want

    def test_pipelined_engine_runs_gemma2(self, gemma2):
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.pp_engine import PipelinedTPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.parallel import make_mesh

        _, params, cfg = gemma2
        tok = ByteTokenizer()
        prompts = ["def g(y):", "assert g("]
        plain = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=256)
        want = plain.generate(prompts, max_new_tokens=8, temperature=0.0)
        eng = PipelinedTPUEngine(params, cfg, tok, batch_size=2,
                                 max_seq_len=256, mesh=make_mesh(pp=2, tp=2))
        got = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == want

    def test_prefix_sharing_exact_with_alternating_windows(self, gemma2):
        """The shared-prefix (context) prefill path must respect per-layer
        windows too — riders attend context + suffix through the same
        alternation."""
        from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

        _, params, cfg = gemma2
        tok = ByteTokenizer()
        shared = "def helper(a, b):\n    return a * b + a - b\n\n" * 4
        prompts = [shared + "assert helper(1, 2) == ", shared + "x = helper("]
        on = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=64,
                            max_seq_len=512, prefix_sharing=True)
        got = on.generate(prompts, max_new_tokens=8, temperature=0.0)
        on.close()
        off = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=64,
                             max_seq_len=512, prefix_sharing=False)
        want = off.generate(prompts, max_new_tokens=8, temperature=0.0)
        off.close()
        assert got == want


class TestSoftcapKernelParity:
    def test_pallas_kernel_softcap_matches_xla(self):
        from reval_tpu.ops.pallas_attention import (
            paged_decode_attention_pallas, paged_decode_attention_xla)

        rng = np.random.default_rng(0)
        b, h, hk, d, page, npages = 2, 4, 2, 16, 8, 6
        q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((npages * page, hk, d)), jnp.float32)
        vp = jnp.asarray(rng.standard_normal((npages * page, hk, d)), jnp.float32)
        tables = jnp.asarray([[1, 2, 0], [3, 4, 5]], jnp.int32)
        lens = jnp.asarray([13, 21], jnp.int32)
        from reval_tpu.ops.pallas_attention import (
            paged_decode_attention_pallas_seq)

        want = paged_decode_attention_xla(q, kp, vp, tables, lens,
                                          page_size=page, softcap=50.0)
        for kernel in (paged_decode_attention_pallas,
                       paged_decode_attention_pallas_seq):
            got = kernel(q, kp, vp, tables, lens, page_size=page,
                         softcap=50.0, interpret=True)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-5, rtol=1e-5)
