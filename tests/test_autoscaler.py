"""SLO-driven autoscaling + per-tenant QoS: the policy state machine,
the weighted-admission math, the programmatic supervisor pool, runtime
ring resize, and the headline tier-1 chaos drill — diurnal peak load ×
replica hard-kill × live autoscaler → zero lost prompts, warming→ready
scale-up, bounded recovery window, then 1→N→1.

Host-only throughout: mock replicas behind a real router over real
HTTP; the autoscaler is driven ONLY by the router's federated
``/metrics`` (the acceptance contract).
"""

import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from reval_tpu.obs import metrics as obs_metrics
from reval_tpu.obs.metrics import parse_prometheus
from reval_tpu.serving import FleetRouter, serve_config
from reval_tpu.serving.autoscaler import (Autoscaler, LocalReplicaProcess,
                                          ScalingPolicy,
                                          mock_replica_factory)
from reval_tpu.serving.router import (OVERFLOW_TENANT, TENANT_LABEL_CAP,
                                      parse_tenant_weights, sanitize_tenant,
                                      weighted_admission)
from reval_tpu.serving.snapshot import write_snapshot
from reval_tpu.serving.supervisor import ReplicaPool

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))


def wait_ready(router, timeout=10.0, n=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ready = router.readiness()
        if ready["ready"] and (n is None or ready["replicas_ready"] >= n):
            return
        time.sleep(0.02)
    raise AssertionError("router replicas never became ready")


def post(port, prompt, tenant=None, max_tokens=32, deadline_s=None,
         timeout=30):
    body = {"prompt": prompt, "max_tokens": max_tokens}
    if tenant is not None:
        body["tenant"] = tenant
    if deadline_s is not None:
        body["deadline_s"] = deadline_s
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def samples_of(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        return parse_prometheus(r.read().decode())


# ---------------------------------------------------------------------------
# ScalingPolicy: hysteresis + cooldown, pure and clock-injected
# ---------------------------------------------------------------------------

def test_policy_boundary_oscillating_signal_never_flaps():
    clock = {"t": 0.0}
    pol = ScalingPolicy(up_consecutive=2, down_consecutive=3,
                        cooldown_s=10.0, clock=lambda: clock["t"])
    # a signal bouncing across the threshold every observation: breach,
    # deadband, breach, deadband … — neither streak ever completes
    for i in range(20):
        clock["t"] += 1.0
        action, indicated, _ = pol.observe(breach=(i % 2 == 0), idle=False)
        assert action is None and indicated is None
    # oscillating breach/idle resets BOTH streaks the same way
    for i in range(20):
        clock["t"] += 1.0
        action, indicated, _ = pol.observe(breach=(i % 2 == 0),
                                           idle=(i % 2 == 1))
        assert action is None and indicated is None


def test_policy_sustained_breach_scales_and_cooldown_holds():
    clock = {"t": 0.0}
    pol = ScalingPolicy(up_consecutive=2, down_consecutive=3,
                        cooldown_s=10.0, clock=lambda: clock["t"])
    assert pol.observe(True, False)[0] is None
    action, _, reason = pol.observe(True, False)
    assert action == "up" and "sustained 2" in reason
    pol.acted()
    # acting reset the streak: the persisting breach first rebuilds it…
    clock["t"] += 1.0
    assert pol.observe(True, False) == (None, None, "steady")
    # …and then the cooldown suppresses the indicated action, SAYING so
    # (the caller counts it blocked)
    for _ in range(4):
        clock["t"] += 1.0
        action, indicated, reason = pol.observe(True, False)
        assert action is None and indicated == "up"
        assert "cooldown" in reason
    clock["t"] += 10.0      # cooldown lapses; streak is already deep
    action, _, _ = pol.observe(True, False)
    assert action == "up"
    pol.acted()
    # idle path mirrors: three consecutive idles → down (post cooldown)
    clock["t"] += 100.0
    for _ in range(2):
        assert pol.observe(False, True)[0] is None
    assert pol.observe(False, True)[0] == "down"


# ---------------------------------------------------------------------------
# Weighted admission: the pure per-tenant shed math
# ---------------------------------------------------------------------------

def test_weighted_admission_math():
    weights = {"alpha": 3.0, "beta": 1.0}
    # ceiling off → always admit
    assert weighted_admission("beta", {"beta": 99}, weights, 0) == "admit"
    # fleet full → shed regardless of share
    assert weighted_admission("alpha", {"alpha": 6, "beta": 2},
                              weights, 8) == "shed_fleet"
    # quota(beta) = ceil(1/4 × 8) = 2; with the fleet past the reserved
    # headroom (8 - 1 = 7), an over-quota tenant sheds FIRST
    assert weighted_admission("beta", {"alpha": 5, "beta": 2},
                              weights, 8) == "shed_tenant"
    # …while an under-quota tenant still admits into the headroom
    assert weighted_admission("alpha", {"alpha": 5, "beta": 2},
                              weights, 8) == "admit"
    # over quota but the fleet has slack → borrowable capacity
    assert weighted_admission("beta", {"beta": 3}, weights, 8) == "admit"
    # unknown tenants weigh 1.0: quota(ghost) = ceil(1/5 × 8) = 2, and at
    # total 7 (past the 8−1 reserve) an over-quota unknown sheds
    assert weighted_admission("ghost", {"alpha": 4, "beta": 1, "ghost": 2},
                              weights, 8) == "shed_tenant"
    # tenant label sanitation: wire garbage folds to the default bucket
    assert sanitize_tenant('we"ird\nname!') == "weirdname"
    assert sanitize_tenant(None) == "default"
    assert sanitize_tenant(123) == "default"


def test_parse_tenant_weights_shapes_and_errors():
    assert parse_tenant_weights("alpha:3,beta:1") == \
        {"alpha": 3.0, "beta": 1.0}
    assert parse_tenant_weights("solo") == {"solo": 1.0}
    assert parse_tenant_weights('{"alpha": 2}') == {"alpha": 2.0}
    assert parse_tenant_weights({"alpha": 2}) == {"alpha": 2.0}
    for bad in ("alpha:abc", '{"alpha": null}', '{"alpha": [1]}',
                ":3", "", "alpha:0", "alpha:-1", '{"alpha"', "[1,2]"):
        with pytest.raises(ValueError):
            parse_tenant_weights(bad)
    # the CLI surfaces the ValueError as a usage error, not a traceback
    import subprocess

    r = subprocess.run(
        [sys.executable, "-m", "reval_tpu", "router", "--mock", "1",
         "--smoke", "1", "--tenant-weights", "alpha:abc"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 1
    assert "tenant-weights" in r.stdout and "Traceback" not in r.stderr


def test_tenant_label_cardinality_is_bounded():
    """A client minting a fresh tenant per request must not grow the
    registry without bound: past the cap, identities fold into the
    shared overflow bucket (metrics AND admission quota)."""
    srv = serve_config({"mock": True}, port=0).start()
    router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                         health_interval_s=0.05).start()
    try:
        wait_ready(router)
        n = TENANT_LABEL_CAP + 8
        for i in range(n):
            post(router.port, f"mint {i}", tenant=f"minted-{i:03d}",
                 max_tokens=8)
        counters = router.statusz()["metrics"]["counters"]
        labels = {k for k in counters
                  if k.startswith(obs_metrics.TENANT_REQUESTS + "{")}
        assert len(labels) == TENANT_LABEL_CAP + 1      # cap + overflow
        overflow_key = (f'{obs_metrics.TENANT_REQUESTS}'
                        f'{{tenant="{OVERFLOW_TENANT}"}}')
        assert counters[overflow_key] == n - TENANT_LABEL_CAP
    finally:
        router.shutdown()
        srv.shutdown()


def test_tenant_weighted_shed_end_to_end():
    """A noisy tenant floods a ceilinged fleet: it sheds (typed 429,
    per-tenant counter) while the quiet tenant keeps serving."""
    srv = serve_config({"mock": True, "mock_echo": True,
                        "mock_step_s": 0.05}, port=0).start()
    router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                         health_interval_s=0.05, max_inflight=4,
                         tenant_weights={"alpha": 3, "beta": 1}).start()
    try:
        wait_ready(router)
        outcomes = {"beta_shed": 0, "beta_ok": 0, "alpha_ok": 0,
                    "alpha_shed": 0}
        lock = threading.Lock()

        def flood(i):
            try:
                post(router.port, f"beta flood {i} " + "pad " * 40,
                     tenant="beta", max_tokens=64)
                with lock:
                    outcomes["beta_ok"] += 1
            except urllib.error.HTTPError as exc:
                body = json.loads(exc.read())
                assert exc.code == 429, body
                assert body["error"]["code"] == "overloaded"
                with lock:
                    outcomes["beta_shed"] += 1

        threads = [threading.Thread(target=flood, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.05)        # the flood is in flight; alpha arrives
        try:
            post(router.port, "alpha quiet " + "pad " * 40,
                 tenant="alpha", max_tokens=64)
            outcomes["alpha_ok"] += 1
        except urllib.error.HTTPError:
            outcomes["alpha_shed"] += 1
        for t in threads:
            t.join(timeout=30)
        assert outcomes["beta_shed"] >= 1, outcomes
        assert outcomes["alpha_ok"] == 1 and not outcomes["alpha_shed"], \
            outcomes
        samples = samples_of(router.port)
        assert samples['reval_tenant_sheds_total{tenant="beta"}'] >= 1
        assert samples['reval_tenant_requests_total{tenant="alpha"}'] == 1
        assert samples.get('reval_tenant_sheds_total{tenant="alpha"}',
                           0) == 0
        # completed forwards fed the labeled e2e histogram + goodput
        assert samples['reval_tenant_e2e_seconds_count{tenant="alpha"}'] \
            == 1
        assert samples[obs_metrics.ROUTER_GOODPUT] >= 1
    finally:
        router.shutdown()
        srv.shutdown()


# ---------------------------------------------------------------------------
# Runtime ring resize (admin add/remove) — in-flight forwards survive
# ---------------------------------------------------------------------------

def admin(port, route, replica, reason=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{route}",
        data=json.dumps({"replica": replica, "reason": reason}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def test_resize_preserves_inflight_forwards_and_shifts_traffic():
    slow = serve_config({"mock": True, "mock_echo": True,
                         "mock_step_s": 0.1}, port=0).start()
    fast = serve_config({"mock": True, "mock_echo": True}, port=0).start()
    slow_id = f"127.0.0.1:{slow.port}"
    fast_id = f"127.0.0.1:{fast.port}"
    router = FleetRouter([slow_id], port=0, health_interval_s=0.05).start()
    try:
        wait_ready(router)
        result = {}

        def inflight():
            result["out"] = post(router.port, "survive the resize",
                                 max_tokens=64, timeout=60)

        th = threading.Thread(target=inflight)
        th.start()
        time.sleep(0.12)        # the forward is mid-decode on `slow`
        out = admin(router.port, "/admin/add_replica", fast_id,
                    reason="autoscaler: test scale-up")
        assert sorted(out["members"]) == sorted([slow_id, fast_id])
        out = admin(router.port, "/admin/remove_replica", slow_id,
                    reason="autoscaler: test scale-down")
        assert out["members"] == [fast_id]
        th.join(timeout=60)
        # the in-flight forward to the REMOVED replica completed intact
        assert result["out"]["choices"][0]["text"]
        # new traffic lands on the surviving member only
        wait_ready(router)
        before = fast._session.engine.stats.prompts
        post(router.port, "after the resize")
        assert fast._session.engine.stats.prompts == before + 1
        status = router.statusz()
        assert status["ring"]["members"] == [fast_id]
        actions = [(e["action"], e["replica"]) for e in status["admin_log"]]
        assert ("add_replica", fast_id) in actions
        assert ("remove_replica", slow_id) in actions
    finally:
        router.shutdown()
        slow.shutdown()
        fast.shutdown()


def test_resize_rejects_duplicates_unknowns_and_last_member():
    srv = serve_config({"mock": True}, port=0).start()
    rid = f"127.0.0.1:{srv.port}"
    router = FleetRouter([rid], port=0, health_interval_s=0.05).start()
    try:
        for route, replica in (("/admin/add_replica", rid),
                               ("/admin/remove_replica", "127.0.0.1:59998"),
                               ("/admin/remove_replica", rid),
                               ("/admin/add_replica", "")):
            with pytest.raises(urllib.error.HTTPError) as err:
                admin(router.port, route, replica)
            assert err.value.code == 400
            body = json.loads(err.value.read())
            assert body["error"]["code"] == "invalid_request"
    finally:
        router.shutdown()
        srv.shutdown()


# ---------------------------------------------------------------------------
# ReplicaPool: programmatic spawn/stop, sticky-failed, postmortems
# ---------------------------------------------------------------------------

class FakeChild:
    """A pool child that dies ``rc`` after ``ttl_s`` unless terminated
    first (terminate = clean exit 0)."""

    def __init__(self, endpoint, rc=1, ttl_s=0.01):
        self.endpoint = endpoint
        self._rc = rc
        self._ttl = ttl_s
        self._stop = threading.Event()

    def wait(self):
        if self._stop.wait(self._ttl):
            return 0
        return self._rc

    def poll(self):
        return 0 if self._stop.is_set() else None

    def terminate(self):
        self._stop.set()


def test_pool_keeps_endpoint_across_respawns_then_goes_sticky(tmp_path):
    spawns = []

    def factory(slot, hint):
        # the endpoint survives respawn via the hint — ring membership
        # must not churn when a child crashes
        endpoint = hint or f"127.0.0.1:{41000 + slot}"
        spawns.append((slot, endpoint))
        return FakeChild(endpoint, rc=9, ttl_s=0.01)

    pool = ReplicaPool(factory, postmortem_dir=str(tmp_path),
                       max_deaths=3, window_s=60.0, base_backoff_s=0.01)
    endpoint = pool.spawn()
    rep = pool.replica(endpoint)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and rep.state != "sticky_failed":
        time.sleep(0.02)
    assert rep.state == "sticky_failed"
    assert rep.rc == 1
    # every respawn re-bound the SAME endpoint
    assert {ep for _, ep in spawns} == {endpoint}
    assert len(spawns) == 3                     # max_deaths spawns
    assert pool.sticky_failed() == [endpoint]
    assert pool.endpoints() == []               # not a live target
    # postmortem-per-death landed on disk
    bundles = [f for f in os.listdir(tmp_path)
               if f.startswith("postmortem-")]
    assert bundles
    with open(tmp_path / bundles[0]) as f:
        assert json.load(f)["reason"] == "supervisor_child_death"
    # a new spawn opens a FRESH slot — the sticky endpoint is never
    # re-targeted
    new_endpoint = pool.spawn()
    assert new_endpoint != endpoint
    assert spawns[-1][0] == 1                   # slot advanced
    pool.close()


def test_pool_graceful_stop_and_real_mock_replica_lifecycle(tmp_path):
    pool = ReplicaPool(mock_replica_factory(), base_backoff_s=0.05,
                       postmortem_dir=str(tmp_path))
    endpoint = pool.spawn()
    assert endpoint in pool.endpoints()
    # the replica actually serves
    port = int(endpoint.rsplit(":", 1)[1])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(f"http://{endpoint}/readyz", timeout=2)
            break
        except Exception:
            time.sleep(0.05)
    out = post(port, "pool replica serves")
    assert out["choices"][0]["text"]
    # a hard kill respawns it at the SAME endpoint
    rep = pool.replica(endpoint)
    rep.supervisor.child.kill()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and rep.supervisor.respawns < 2:
        time.sleep(0.02)
    assert rep.supervisor.respawns >= 2
    assert rep.endpoint == endpoint
    # graceful stop: exit 0, supervisor stays stopped, endpoint retires
    pool.stop(endpoint)
    assert rep.state == "stopped" and rep.rc == 0
    assert pool.endpoints() == []


# ---------------------------------------------------------------------------
# The autoscaler against a live mock fleet
# ---------------------------------------------------------------------------

def saturate(port, n, prompt_pad=60, max_tokens=48):
    def one(i):
        try:
            post(port, f"pressure {i} " + "pad " * prompt_pad,
                 max_tokens=max_tokens, timeout=30)
        except urllib.error.HTTPError as exc:
            exc.read()      # sheds are the signal, not a failure
    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)


def test_autoscaler_scales_1_to_n_to_1_driven_by_metrics_only(tmp_path):
    pool = ReplicaPool(
        mock_replica_factory({"max_queued_tokens": 400,
                              "mock_step_s": 0.01}),
        postmortem_dir=str(tmp_path), base_backoff_s=0.05)
    ep0 = pool.spawn()
    router = FleetRouter([ep0], port=0, health_interval_s=0.05).start()
    asc = Autoscaler(f"127.0.0.1:{router.port}", pool, ttft_p99_s=0.05,
                     interval_s=0.1, cooldown_s=0.5, min_replicas=1,
                     max_replicas=2, up_consecutive=2, down_consecutive=4,
                     drain_wait_s=5.0)
    try:
        wait_ready(router)
        for _ in range(20):
            saturate(router.port, 12)
            if asc.step() == "up":
                break
        assert asc.counters()["up"] == 1, asc.counters()
        members = router.statusz()["ring"]["members"]
        assert len(members) == 2 and ep0 in members
        added = next(m for m in members if m != ep0)
        assert added in pool.endpoints()
        # idle → (after down_consecutive quiet observations + cooldown)
        # drain back to 1; min_replicas then pins it there
        time.sleep(0.6)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and asc.counters()["down"] < 1:
            asc.step()
            time.sleep(0.05)
        assert asc.counters()["down"] == 1, asc.counters()
        assert router.statusz()["ring"]["members"] == [ep0]
        assert added not in pool.endpoints()    # stopped, gracefully
        assert pool.replica(added).rc == 0
        # the scale-down took the graceful path: drain BEFORE remove
        log = [e for e in router.statusz()["admin_log"]
               if e["replica"] == added]
        actions = [e["action"] for e in log]
        assert actions.index("drain") < actions.index("remove_replica")
        assert all("autoscaler" in (e["reason"] or "") for e in log)
        # continued idling never flaps: down is indicated but blocked at
        # min_replicas, never acted
        for _ in range(8):
            assert asc.step() is None
            time.sleep(0.02)
        assert asc.counters()["down"] == 1
        assert len(router.statusz()["ring"]["members"]) == 1
    finally:
        asc.stop()
        router.shutdown()
        pool.close()


def test_autoscaler_removes_sticky_failed_and_never_retargets(tmp_path):
    """A sticky-failed pool replica leaves the ring via the reconcile
    step, and scale-up spawns a FRESH replica instead of reusing it."""
    live_cfg = {"mock": True, "mock_echo": True}
    base = mock_replica_factory()

    def factory(slot, hint):
        if slot == 0:
            return LocalReplicaProcess(live_cfg,
                                       port=int(hint.rsplit(":", 1)[1])
                                       if hint else 0)
        if slot == 1:
            return FakeChild(hint or "127.0.0.1:41999", rc=7, ttl_s=0.01)
        return base(slot, hint)

    pool = ReplicaPool(factory, postmortem_dir=str(tmp_path),
                       max_deaths=2, window_s=60.0, base_backoff_s=0.01)
    ep0 = pool.spawn()
    bad = pool.spawn()      # dies into sticky_failed almost immediately
    rep = pool.replica(bad)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and rep.state != "sticky_failed":
        time.sleep(0.02)
    assert rep.state == "sticky_failed"
    router = FleetRouter([ep0, bad], port=0, health_interval_s=0.05).start()
    asc = Autoscaler(f"127.0.0.1:{router.port}", pool, interval_s=0.1,
                     # any observed TTFT breaches: the next saturate
                     # round forces a deterministic scale-up
                     ttft_p99_s=0.0001,
                     cooldown_s=0.2, min_replicas=1, max_replicas=3,
                     up_consecutive=1, down_consecutive=50)
    try:
        wait_ready(router)
        asc.step()
        assert bad not in router.statusz()["ring"]["members"]
        assert any(a["action"] == "remove_sticky" for a in asc.actions)
        # force a scale-up: the spawned replica is a fresh slot, never
        # the sticky endpoint
        saturate(router.port, 8, prompt_pad=200)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and asc.counters()["up"] < 1:
            saturate(router.port, 8, prompt_pad=200)
            asc.step()
        members = router.statusz()["ring"]["members"]
        assert bad not in members
        assert len(members) == 2
    finally:
        asc.stop()
        router.shutdown()
        pool.close()


# ---------------------------------------------------------------------------
# THE chaos drill: diurnal peak × hard-kill × autoscaler
# ---------------------------------------------------------------------------

def test_chaos_drill_diurnal_peak_hard_kill_autoscaler(tmp_path,
                                                       monkeypatch):
    """The ISSUE 14 acceptance scenario, host-only: diurnal load peaking
    mid-run against a 1-replica mock fleet with the autoscaler live;
    the original replica is HARD-killed mid-peak (after scale-up).
    Asserts: zero lost prompts (complete ledger), the scale-up replica
    booted warming→ready with zero fresh AOT compiles, the recovery
    window is bounded, the kill really respawned through the
    supervisor, and the fleet later drains back to one replica."""
    from loadgen import OpenLoopRunner, build_workload, diurnal_arrivals, \
        synthetic_tenants

    aot_dir = tmp_path / "aot"
    snap_dir = tmp_path / "snap"
    snap_dir.mkdir()
    monkeypatch.setenv("REVAL_TPU_AOT_CACHE_DIR", str(aot_dir))

    # pre-warm the AOT cache (one throwaway engine compiles + stores the
    # two mock programs) and pre-seed slot 1's warm-state snapshot, so
    # the SCALE-UP replica boots the full PR-10 warm path
    from reval_tpu.serving.mock_engine import MockStepEngine

    warm = MockStepEngine()
    assert warm.fresh_compiles == 2
    chains = [[(17 * (i + 1) + j) % 251 for j in range(128)]
              for i in range(3)]
    assert write_snapshot(str(snap_dir / "r1.json"),
                          {"prefix_chains": chains, "template_stats": {}})

    made: dict[int, list] = {}
    base = mock_replica_factory(
        {"max_queued_tokens": 1200, "mock_step_s": 0.01},
        per_slot={0: {"snapshot_path": str(snap_dir / "r0.json")},
                  1: {"snapshot_path": str(snap_dir / "r1.json"),
                      "mock_rewarm_s": 0.02}})

    def factory(slot, hint):
        proc = base(slot, hint)
        made.setdefault(slot, []).append(proc)
        return proc

    pool = ReplicaPool(factory, postmortem_dir=str(tmp_path / "pm"),
                       base_backoff_s=0.05, max_deaths=5, window_s=60.0)
    ep0 = pool.spawn()
    router = FleetRouter([ep0], port=0, health_interval_s=0.05,
                         eject_fails=2, cooldown_s=0.3).start()
    asc = Autoscaler(f"127.0.0.1:{router.port}", pool, ttft_p99_s=0.08,
                     interval_s=0.15, cooldown_s=1.0, min_replicas=1,
                     max_replicas=2, up_consecutive=2, down_consecutive=6,
                     drain_wait_s=5.0).start()
    killed = {}

    def assassin():
        # strike mid-peak, once the autoscaler has brought the second
        # replica in (the fleet must absorb the loss, not just retry it)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if len(router.statusz()["ring"]["members"]) == 2:
                proc = pool.replica(ep0).supervisor.child
                proc.kill()
                killed["at"] = time.monotonic()
                return
            time.sleep(0.02)

    try:
        wait_ready(router)
        arrivals = diurnal_arrivals(10.0, 90.0, 3.0, random.Random(14))
        assert len(arrivals) >= 80
        tenants = synthetic_tenants({"alpha": 3, "beta": 1},
                                    deadline_s=8.0, template_chars=500)
        requests = build_workload(arrivals, tenants, random.Random(14))
        hit = threading.Thread(target=assassin)
        hit.start()
        runner = OpenLoopRunner(f"127.0.0.1:{router.port}", requests,
                                concurrency=64, slo_e2e_s=5.0,
                                timeline_bucket_s=0.5)
        art = runner.run()
        hit.join(timeout=30)

        # -- zero lost prompts, ledger complete ---------------------------
        assert art["ledger_complete"] is True
        assert art["counts"]["lost"] == 0, art["counts"]
        assert art["goodput"]["completed"] == len(requests)
        assert killed, "the assassin never fired — drill exercised nothing"

        # -- the kill went through the supervisor: respawn at the same
        #    endpoint, postmortem on disk --------------------------------
        assert pool.replica(ep0).supervisor.respawns >= 2
        assert pool.replica(ep0).endpoint == ep0
        assert any(f.startswith("postmortem-")
                   for f in os.listdir(tmp_path / "pm"))

        # -- the autoscaler acted, from /metrics only --------------------
        assert asc.counters()["up"] >= 1, asc.counters()
        log = router.statusz()["admin_log"]
        adds = [e for e in log if e["action"] == "add_replica"]
        assert adds and all("autoscaler" in (e["reason"] or "")
                            for e in adds)

        # -- the scale-up replica served via warming→ready with ZERO
        #    fresh AOT compiles ------------------------------------------
        assert 1 in made, "no scale-up replica was ever spawned"
        scale_up = made[1][0]
        eng = scale_up.server._session.engine
        assert eng.fresh_compiles == 0          # AOT cache hits only
        counters = eng.stats.registry.snapshot()["counters"]
        assert counters.get(obs_metrics.RESTART_WARM_PREFIXES, 0) \
            == len(chains)                      # snapshot replayed
        hists = eng.stats.registry.snapshot()["histograms"]
        assert hists[obs_metrics.RESTART_TO_READY]["count"] >= 1
        assert eng.stats.prompts > 0            # and it actually served

        # -- SLOs hold outside a bounded recovery window ------------------
        assert art["recovery"]["worst_bad_window_s"] <= 2.0, \
            art["recovery"]
        assert art["slo"]["attainment"]["e2e"] >= 0.9, art["slo"]
        assert art["goodput"]["ratio"] >= 0.9

        # -- the artifact proves the traffic was real: both tenants,
        #    per-minute(-bucket) timeline covered -------------------------
        assert set(art["tenants"]) == {"alpha", "beta"}
        assert sum(r["arrivals"] for r in art["timeline"]) == len(requests)

        # -- and the fleet drains back to 1 (N→1), gracefully -------------
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and asc.counters()["down"] < 1:
            time.sleep(0.1)
        assert asc.counters()["down"] >= 1, asc.counters()
        assert len(router.statusz()["ring"]["members"]) == 1
        drained = [e["action"] for e in router.statusz()["admin_log"]
                   if e["replica"] != ep0]
        assert drained.index("drain") < drained.index("remove_replica")

        # the federated exposition still parses end to end
        samples = samples_of(router.port)
        assert samples[obs_metrics.ROUTER_REQUESTS] >= len(requests)
    finally:
        asc.stop()
        router.shutdown()
        pool.close()


# ---------------------------------------------------------------------------
# watch: the fleet-load view renders tenants + autoscaler actions
# ---------------------------------------------------------------------------

def test_watch_fleet_load_view_renders_tenants_and_autoscaler(capsys):
    from reval_tpu.watch import run_watch

    srv = serve_config({"mock": True, "mock_echo": True}, port=0).start()
    router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                         health_interval_s=0.05,
                         tenant_weights={"alpha": 3}).start()
    try:
        wait_ready(router)
        post(router.port, "watch alpha " + "pad " * 30, tenant="alpha",
             deadline_s=20)
        post(router.port, "watch beta " + "pad " * 30, tenant="beta",
             deadline_s=20)
        router.add_replica("127.0.0.1:59997",
                           reason="autoscaler: breach sustained")
        rc = run_watch(["--port", str(router.port), "--interval", "0.01",
                        "--iterations", "2", "--no-clear",
                        "--slo-e2e", "5.0"])
    finally:
        router.shutdown()
        srv.shutdown()
    assert rc == 0
    out = capsys.readouterr().out
    assert "load" in out and "goodput 2" in out
    assert "attainment(e2e≤5s)" in out
    assert "tenant       alpha" in out and "tenant       beta" in out
    assert "autoscaler" in out
    assert "add_replica" in out and "breach sustained" in out
