"""One-deep chunk pipelining (paged engine): dispatching chunk i+1 before
fetching chunk i's tokens must be invisible in outputs — same programs,
same inputs, only the host fetch ordering changes — while actually
overlapping (stats.pipelined_chunks > 0).

The serial baseline (pipeline=False) is the pre-pipeline engine: fetch
immediately after every dispatch.  Reference analogue: vLLM's engine
step loop is fully serial per step (the reference drives it one prompt
at a time, inference.py:90-104); the pipeline is TPU-tunnel-first
design with no reference counterpart.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params

PAGE = 128

PROMPTS = [
    "def add(a, b):\n    return a + b\nassert add(",
    "x = 1",
    "for i in range(10):\n    print(i)",
    "y = [k * k for k in range(5)]",
]


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    return cfg, params


@pytest.fixture(scope="module")
def engines(tiny):
    cfg, params = tiny
    piped = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512, pipeline=True)
    serial = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                            page_size=PAGE, max_seq_len=512, pipeline=False)
    yield piped, serial
    piped.close()
    serial.close()


def test_long_generation_crosses_pages_and_pipelines(engines):
    """360 new tokens cross three page boundaries: the 128/256 crossings
    coincide with pow2 span-bucket growth (1→2→4, full repack via
    flush), while the 384 crossing lands inside the span-4 plateau and
    must ride the pipeline as an in-place device table patch."""
    piped, serial = engines
    want = serial.generate(PROMPTS[:2], max_new_tokens=360, temperature=0.0)
    got = piped.generate(PROMPTS[:2], max_new_tokens=360, temperature=0.0)
    assert got == want
    assert piped.stats.pipelined_chunks > 0
    assert piped.stats.patched_tables > 0    # plateau crossing: no flush
    assert serial.stats.pipelined_chunks == 0
    assert serial.stats.patched_tables == 0


def test_more_prompts_than_slots_parity(engines):
    piped, serial = engines
    want = serial.generate(PROMPTS * 2, max_new_tokens=40, temperature=0.0)
    got = piped.generate(PROMPTS * 2, max_new_tokens=40, temperature=0.0)
    assert got == want


def test_stop_string_parity(engines):
    """A stop hit while the next chunk is in flight discards that chunk's
    tokens for the stopped slot — output must equal the serial engine's."""
    piped, serial = engines
    fulls = serial.generate(PROMPTS, max_new_tokens=48, temperature=0.0)
    pick = next((i for i, f in enumerate(fulls) if len(f) > 6), None)
    assert pick is not None, f"random model produced no text: {fulls!r}"
    stop = fulls[pick][4:6]
    want = serial.generate(PROMPTS, max_new_tokens=48, stop=[stop],
                           temperature=0.0)
    got = piped.generate(PROMPTS, max_new_tokens=48, stop=[stop],
                         temperature=0.0)
    assert got == want


def test_sampled_parity(engines):
    """fold_in(key, position) sampling is position-stable, so pipelining
    cannot shift the stream."""
    import jax

    piped, serial = engines
    # generate() advances the engine key per call and earlier tests call
    # the two engines unequally often — pin both streams to the same key
    piped._key = jax.random.PRNGKey(7)
    serial._key = jax.random.PRNGKey(7)
    want = serial.generate(PROMPTS[:2], max_new_tokens=40, temperature=0.9,
                           top_k=8)
    got = piped.generate(PROMPTS[:2], max_new_tokens=40, temperature=0.9,
                         top_k=8)
    assert got == want


def test_preemption_parity(tiny):
    """Pool smaller than slots x max_len: preemption (which frees and
    reallocates pages) must still be fenced from in-flight chunks."""
    cfg, params = tiny
    kw = dict(max_slots=2, page_size=PAGE, max_seq_len=512, num_pages=5)
    piped = PagedTPUEngine(params, cfg, ByteTokenizer(), pipeline=True, **kw)
    serial = PagedTPUEngine(params, cfg, ByteTokenizer(), pipeline=False,
                            **kw)
    want = serial.generate(PROMPTS, max_new_tokens=96, temperature=0.0)
    got = piped.generate(PROMPTS, max_new_tokens=96, temperature=0.0)
    assert got == want
    piped.close()
    serial.close()


def test_env_var_disables(tiny, monkeypatch):
    cfg, params = tiny
    monkeypatch.setenv("REVAL_TPU_PIPELINE", "0")
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                         page_size=PAGE, max_seq_len=256)
    assert eng.pipeline is False
    monkeypatch.delenv("REVAL_TPU_PIPELINE")
    eng2 = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                          page_size=PAGE, max_seq_len=256)
    assert eng2.pipeline is True
    eng.close()
    eng2.close()


def test_fuzz_parity(engines):
    """Randomized scenarios: any interleaving of stops, budgets, and
    sampling must be invisible to outputs.  max_new values are chosen to
    exercise uneven chunk tails (8+32+8 and 8+32+32+16+8) without
    exploding the compiled (steps, span) shape set."""
    import jax

    piped, serial = engines
    rng = np.random.default_rng(0)
    pool = PROMPTS + ["while x:", "import os\n" * 2, "z = {'a': 1}"]
    for case in range(6):
        n = int(rng.integers(1, 5))
        prompts = [pool[i] for i in rng.integers(0, len(pool), n)]
        max_new = int(rng.choice([48, 96]))
        temp = float(rng.choice([0.0, 0.8]))
        stop = None
        if rng.random() < 0.4:
            # probe at the CASE's temperature with the case's key so the
            # derived stop actually occurs in the compared streams —
            # a greedy-probed stop would never fire in a sampled case
            serial._key = jax.random.PRNGKey(100 + case)
            probe = serial.generate(prompts[:1], max_new_tokens=max_new,
                                    temperature=temp)[0]
            if len(probe) > 4:
                stop = [probe[2:4]]
        piped._key = jax.random.PRNGKey(100 + case)
        serial._key = jax.random.PRNGKey(100 + case)
        kw = dict(max_new_tokens=max_new, temperature=temp, stop=stop)
        want = serial.generate(prompts, **kw)
        got = piped.generate(prompts, **kw)
        assert got == want, f"case {case}: {prompts!r} {kw!r}"
