"""Autotuned kernel defaults: tools/decide_defaults.py picks the winning
(backend, dot-mode) from recorded artifacts, and the dispatcher's
env-unset fallback applies the persisted decision."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.decide_defaults import decide


def _write(d, name, obj):
    with open(os.path.join(d, name), "w") as f:
        json.dump(obj, f)


def test_full_pipeline_tier_outranks_kernel_ab(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "kernel_ab.txt"), "w") as f:
        # kernel-only rows say seq-wide wins...
        f.write("grid        10.000 ms/step   1.0 GB/s effective\n"
                "seq-wide     2.000 ms/step   5.0 GB/s effective\n")
    # ...but the full pipeline says the seq backend (swap) is best
    _write(d, "bench_quick.json", {"value": 3.0})
    _write(d, "bench_direct_seqk.json", {"value": 5.5})
    _write(d, "bench_direct_wide.json", {"value": 4.0})
    got = decide(d)
    assert got["REVAL_TPU_PAGED_BACKEND"] == "pallas_seq"
    assert got["REVAL_TPU_KERNEL_DOT"] == "swap"
    assert got["evidence"]["tier"] == "full-pipeline"
    assert got["evidence"]["probes_per_sec"] == 5.5
    assert got["bench_args"] == {}

    # a winning kv8s64 run carries its bench-level config for bench.py
    _write(d, "bench_direct_kv8s64.json", {"value": 7.0})
    got = decide(d)
    assert got["bench_args"] == {"kv_dtype": "int8", "slots": 64}


def test_kernel_ab_fallback_and_error_rows_skipped(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "kernel_ab.txt"), "w") as f:
        f.write("grid           FAILED: MosaicError: ...\n"
                "seq             7.100 ms/step   12.0 GB/s effective\n"
                "grid-wide       6.200 ms/step   14.0 GB/s effective\n")
    # error bench artifacts must not decide anything
    _write(d, "bench_quick.json", {"value": 0.0, "error": "tpu-unreachable"})
    got = decide(d)
    assert got["REVAL_TPU_PAGED_BACKEND"] == "pallas"
    assert got["REVAL_TPU_KERNEL_DOT"] == "wide"
    assert got["evidence"]["tier"] == "kernel-ab"


def test_no_artifacts_decides_nothing(tmp_path):
    assert decide(str(tmp_path)) is None


def test_all_pallas_dead_falls_back_to_xla(tmp_path):
    """Every Mosaic variant rejected by the chip helper → the emergency
    xla tier (kernel row or bench artifact) still yields a working
    decision instead of none."""
    d = str(tmp_path)
    with open(os.path.join(d, "kernel_ab.txt"), "w") as f:
        f.write("grid           FAILED: MosaicError: ...\n"
                "seq            FAILED: MosaicError: ...\n"
                "xla             106.335 ms/step     37.9 GB/s effective\n")
    got = decide(d)
    assert got["REVAL_TPU_PAGED_BACKEND"] == "xla"

    _write(d, "bench_direct_xlab.json", {"value": 0.9})
    got = decide(d)
    assert got["REVAL_TPU_PAGED_BACKEND"] == "xla"
    assert got["evidence"]["tier"] == "full-pipeline"


def test_main_writes_autotune_and_env_files(tmp_path):
    from tools.decide_defaults import main

    d = str(tmp_path)
    _write(d, "bench_quick.json", {"value": 3.3})
    assert main(["--watch", d]) == 0
    with open(os.path.join(d, "autotune.json")) as f:
        tuned = json.load(f)
    assert tuned["REVAL_TPU_PAGED_BACKEND"] == "pallas"
    assert "decided_at" in tuned
    env = open(os.path.join(d, "decided_env.sh")).read()
    assert "export REVAL_TPU_PAGED_BACKEND=pallas" in env
    assert "export REVAL_TPU_KERNEL_DOT=swap" in env


def test_main_no_artifacts_rc1(tmp_path):
    from tools.decide_defaults import main

    assert main(["--watch", str(tmp_path)]) == 1
    assert not os.path.exists(os.path.join(str(tmp_path), "autotune.json"))


def test_dispatcher_env_unset_uses_autotune_file(tmp_path, monkeypatch):
    from reval_tpu.ops import pallas_attention as pa

    path = os.path.join(str(tmp_path), "autotune.json")
    _write(str(tmp_path), "autotune.json",
           {"REVAL_TPU_PAGED_BACKEND": "xla",
            "REVAL_TPU_KERNEL_DOT": "wide"})
    monkeypatch.setenv("REVAL_TPU_AUTOTUNE_FILE", path)
    monkeypatch.delenv("REVAL_TPU_PAGED_BACKEND", raising=False)
    monkeypatch.delenv("REVAL_TPU_KERNEL_DOT", raising=False)
    pa._AUTOTUNE_CACHE.clear()
    assert pa._autotune_defaults() == {"REVAL_TPU_PAGED_BACKEND": "xla",
                                       "REVAL_TPU_KERNEL_DOT": "wide"}

    # dispatch actually routes to the decided backend: xla here, so the
    # call works on CPU with no pallas interpret plumbing
    import jax.numpy as jnp
    import numpy as np

    b, h, h_kv, d, p = 2, 4, 2, 128, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((3 * p, h_kv, d)), jnp.float32)
    tables = jnp.asarray([[1, 2], [2, 1]], jnp.int32)
    lens = jnp.asarray([10, 20], jnp.int32)
    out = pa.paged_decode_attention(q, kp, kp, tables, lens, page_size=p)
    ref = pa.paged_decode_attention_xla(q, kp, kp, tables, lens, page_size=p)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    # explicit env always outranks the autotune file
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "xla")
    out2 = pa.paged_decode_attention(q, kp, kp, tables, lens, page_size=p)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref))


def test_autotune_missing_or_garbage_is_empty(tmp_path, monkeypatch):
    from reval_tpu.ops import pallas_attention as pa

    missing = os.path.join(str(tmp_path), "nope.json")
    monkeypatch.setenv("REVAL_TPU_AUTOTUNE_FILE", missing)
    pa._AUTOTUNE_CACHE.clear()
    assert pa._autotune_defaults() == {}

    bad = os.path.join(str(tmp_path), "bad.json")
    with open(bad, "w") as f:
        f.write("{not json")
    monkeypatch.setenv("REVAL_TPU_AUTOTUNE_FILE", bad)
    pa._AUTOTUNE_CACHE.clear()
    assert pa._autotune_defaults() == {}
    pa._AUTOTUNE_CACHE.clear()
