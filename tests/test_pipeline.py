"""Pipeline parallelism: GPipe prefill + token-ring decode vs single-device.

Runs on the virtual 8-device CPU mesh (conftest sets JAX_PLATFORMS=cpu and
xla_force_host_platform_device_count=8).  The oracle is the non-pipelined
model: same params, same inputs, bitwise-deterministic greedy decode.
"""

from __future__ import annotations

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax
import jax.numpy as jnp

from reval_tpu.inference.tpu.engine import TPUEngine
from reval_tpu.inference.tpu.pp_engine import PipelinedTPUEngine
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import (
    ModelConfig,
    decode_step,
    init_kv_cache,
    init_random_params,
    prefill,
)
from reval_tpu.parallel import make_mesh
from reval_tpu.parallel.pipeline import (
    pipeline_decode_chunk,
    pipeline_prefill,
    pp_param_specs,
    shard_params_pp,
)


def small_cfg(**kw):
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_layers=4, num_heads=4, num_kv_heads=2, head_dim=16)
    base.update(kw)
    return ModelConfig(**base)


def make_inputs(cfg, b, t, seed=0):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(2, cfg.vocab_size, (b, t)), jnp.int32)
    pad = jnp.asarray(rng.integers(0, t // 2, (b,)), jnp.int32)
    # left-pad rows with pad_id 0 as the engine would
    mask = jnp.arange(t)[None, :] < pad[:, None]
    tokens = jnp.where(mask, 0, tokens)
    return tokens, pad


@pytest.mark.parametrize("pp,n_micro", [(2, 2), (4, 4), (2, 4)])
def test_pipeline_prefill_matches_single_device(pp, n_micro):
    cfg = small_cfg()
    params = init_random_params(cfg, seed=0, dtype="float32")
    b, t = 8, 16
    tokens, pad = make_inputs(cfg, b, t)

    ref_cache = init_kv_cache(cfg, b, t + 4, dtype=jnp.float32)
    ref_logits, ref_cache = prefill(params, cfg, tokens, pad, ref_cache,
                                    logits_mode="last")

    mesh = make_mesh(pp=pp)
    mb = b // n_micro
    pcache = init_kv_cache(cfg, b + mb, t + 4, dtype=jnp.float32)
    sharded = shard_params_pp(params, cfg, mesh)
    logits, cache = pipeline_prefill(sharded, cfg, tokens, pad, pcache,
                                     mesh, n_micro)

    np.testing.assert_allclose(np.asarray(logits[:, 0, :]),
                               np.asarray(ref_logits[:, 0, :]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(cache.k[:, :b, :t]),
                               np.asarray(ref_cache.k[:, :, :t]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(cache.v[:, :b, :t]),
                               np.asarray(ref_cache.v[:, :, :t]),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_decode_chunk_matches_single_device():
    cfg = small_cfg()
    params = init_random_params(cfg, seed=1, dtype="float32")
    pp, b, t, steps = 4, 8, 16, 6
    tokens, pad = make_inputs(cfg, b, t, seed=3)

    # reference: prefill then greedy decode token by token
    ref_cache = init_kv_cache(cfg, b, t + steps + 2, dtype=jnp.float32)
    ref_logits, ref_cache = prefill(params, cfg, tokens, pad, ref_cache,
                                    logits_mode="last")
    first = jnp.argmax(ref_logits[:, 0, :], axis=-1).astype(jnp.int32)
    ref_toks = []
    token, pos, cache = first[:, None], jnp.int32(t), ref_cache
    for _ in range(steps):
        logits, cache = decode_step(params, cfg, token, pad, cache, pos)
        token = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        ref_toks.append(np.asarray(token[:, 0]))
        pos = pos + 1
    ref_toks = np.stack(ref_toks, axis=1)            # [B, steps]

    mesh = make_mesh(pp=pp)
    sharded = shard_params_pp(params, cfg, mesh)
    mb = b // pp
    pcache = init_kv_cache(cfg, b + mb, t + steps + 2, dtype=jnp.float32)
    plogits, pcache = pipeline_prefill(sharded, cfg, tokens, pad, pcache,
                                       mesh, n_micro=pp)
    pfirst = jnp.argmax(plogits[:, 0, :], axis=-1).astype(jnp.int32)
    np.testing.assert_array_equal(np.asarray(pfirst), np.asarray(first))

    toks, pcache, last = pipeline_decode_chunk(
        sharded, cfg, pfirst[:, None], pad, pcache, jnp.int32(t),
        jnp.float32(0.0), jax.random.PRNGKey(0), mesh, steps=steps)
    np.testing.assert_array_equal(np.asarray(toks), ref_toks)
    np.testing.assert_array_equal(np.asarray(last[:, 0]), ref_toks[:, -1])


def test_pipeline_specs_shard_layer_dim():
    from reval_tpu.parallel import param_specs

    cfg = small_cfg()
    params = init_random_params(cfg, seed=0, dtype="float32")
    mesh = make_mesh(pp=2, tp=2)
    specs = pp_param_specs(params, cfg, mesh)
    assert specs["layers"]["q_w"][0] == "pp"
    assert specs["layers"]["q_w"][2] == "tp"      # tp rule preserved
    # top-level leaves keep the base (non-pp) rules: replicated across stages
    base = param_specs(params, cfg, mesh)
    assert specs["embed"] == base["embed"]
    assert "pp" not in jax.tree_util.tree_leaves(
        [list(specs[k]) for k in specs if k != "layers"])


def test_pipelined_engine_matches_plain_engine():
    cfg = small_cfg(vocab_size=ByteTokenizer.vocab_size + 61)  # keep 256+ ids
    params = init_random_params(cfg, seed=2, dtype="float32")
    tok = ByteTokenizer()
    prompts = ["def add(a, b):", "x = 1\ny =", "assert add(", "print("]

    plain = TPUEngine(params, cfg, tok, batch_size=4, max_seq_len=128)
    want = plain.generate(prompts, max_new_tokens=12, temperature=0.0)

    mesh = make_mesh(pp=2)
    eng = PipelinedTPUEngine(params, cfg, tok, batch_size=4, max_seq_len=128,
                             mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=12, temperature=0.0)
    assert got == want


def test_pipelined_engine_with_tp_axis():
    """pp × tp composition: manual over pp, GSPMD over tp."""
    cfg = small_cfg(vocab_size=ByteTokenizer.vocab_size + 61)
    params = init_random_params(cfg, seed=4, dtype="float32")
    tok = ByteTokenizer()
    prompts = ["def f(x):", "return x +"]

    plain = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=128)
    want = plain.generate(prompts, max_new_tokens=8, temperature=0.0)

    mesh = make_mesh(pp=2, tp=2)
    eng = PipelinedTPUEngine(params, cfg, tok, batch_size=2, max_seq_len=128,
                             mesh=mesh)
    got = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    assert got == want


def test_pipelined_engine_filtered_sampling():
    """top_k=1 at temperature>0 must reproduce greedy: proves the
    top-k/nucleus filter is actually compiled into the last ring stage
    (and that the kwarg plumbing through _pp_decode_chunk holds — a
    missing `filtered` static broke the whole pp path once)."""
    cfg = small_cfg(vocab_size=ByteTokenizer.vocab_size + 61)
    params = init_random_params(cfg, seed=2, dtype="float32")
    tok = ByteTokenizer()
    prompts = ["def add(a, b):", "x = 1\ny =", "assert add(", "print("]

    mesh = make_mesh(pp=2)
    eng = PipelinedTPUEngine(params, cfg, tok, batch_size=4, max_seq_len=128,
                             mesh=mesh)
    greedy = eng.generate(prompts, max_new_tokens=12, temperature=0.0)
    got = eng.generate(prompts, max_new_tokens=12, temperature=1.7, top_k=1)
    assert got == greedy
