"""Warm restarts: AOT executable cache, snapshot/restore, supervisor,
and the rolling-restart drill (ISSUE 10).

Everything here is host-only.  The AOT cache's degraded-path state
machine (corrupt payload, fingerprint mismatch, unwritable dir, GC) is
exercised through the real :class:`AOTCache` with a mock payload codec;
the real ``jax.export`` round trip runs against a tiny jitted function;
and the headline drill drives mock replicas behind a real
:class:`FleetRouter`: drain → graceful stop (snapshot) → supervised
restart → ``/readyz`` flips via ``warming`` with AOT hits and ZERO
fresh compiles → router rejoin → second-replica hard-kill mid-fleet →
zero lost prompts, task logs byte-identical to a no-restart run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from reval_tpu.inference.client import HTTPClientBackend
from reval_tpu.inference.tpu.aot_cache import (AOTCache, AotJit, FORMAT,
                                               fingerprint,
                                               kernel_export_skip,
                                               runtime_context)
from reval_tpu.obs import metrics as obs_metrics
from reval_tpu.serving import FleetRouter, Supervisor, serve_config
from reval_tpu.serving.snapshot import (FORMAT as SNAP_FORMAT,
                                        read_snapshot, write_snapshot)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TEMPLATE_A = "few-shot warm template alpha | " * 40
TEMPLATE_B = "few-shot warm template bravo | " * 40

FAST_RETRY = {"max_attempts": 10, "base_delay": 0.02,
              "max_delay": 0.3, "jitter": 0.1}


def mock_codec(payload: bytes):
    doc = json.loads(payload)
    if not isinstance(doc, dict) or "entry" not in doc:
        raise ValueError("not a mock AOT payload")
    return lambda: doc["entry"]


def store_mock(cache: AOTCache, entry: str, fp: str, sig=("s",),
               compile_s: float = 0.5) -> None:
    cache.store(entry, sig, fp, json.dumps({"entry": entry}).encode(),
                compile_s, signature_repr=repr(sig))


# ---------------------------------------------------------------------------
# AOTCache: the degraded-path state machine (mock codec, host-only)
# ---------------------------------------------------------------------------

def test_aot_cache_store_load_hit_counts_and_saves(tmp_path):
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint({"m": "tiny"})
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec) is None
    assert cache.misses == 1                    # cold
    store_mock(cache, "prog.a", fp, compile_s=2.5)
    fn = cache.load("prog.a", ("s",), fp, deserialize=mock_codec)
    assert fn is not None and fn() == "prog.a"
    assert cache.hits == 1
    assert cache.compile_s_saved == 2.5
    row = cache.counters()
    assert row["entries"] == 1 and row["bytes"] > 0


def test_aot_cache_corrupt_payload_degrades_to_miss(tmp_path):
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint({"m": "tiny"})
    store_mock(cache, "prog.a", fp)
    payload = [p for p in os.listdir(cache.dir) if p.endswith(".bin")][0]
    with open(os.path.join(cache.dir, payload), "wb") as f:
        f.write(b"garbage not the payload")    # checksum now wrong
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec) is None
    assert cache.errors == 1 and cache.misses == 1


def test_aot_cache_fingerprint_mismatch_degrades_to_miss(tmp_path):
    # a DIFFERENT fingerprint normally resolves to a different file
    # (the fp is part of the file key — configs coexist, see below), so
    # the meta-level check is defense in depth: tamper the stored
    # meta's fingerprint in place to exercise it
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint({"jax": "0.4.0"})
    store_mock(cache, "prog.a", fp)
    meta_path = cache._base("prog.a", ("s",), fp) + ".json"
    with open(meta_path) as f:
        meta = json.load(f)
    meta["fingerprint"] = fingerprint({"jax": "0.5.0"})     # toolchain moved
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    assert cache.load("prog.a", ("s",), fp,
                      deserialize=mock_codec) is None
    assert cache.errors == 1 and cache.misses == 1


def test_aot_cache_distinct_fingerprints_coexist(tmp_path):
    # two engine configs with IDENTICAL call signatures over one shared
    # dir (e.g. xla- and pallas-backed boots alternating) must not
    # clobber each other's entries: the fingerprint is part of the file
    # key, so both stay warm
    cache = AOTCache(str(tmp_path / "aot"))
    fp_a = fingerprint({"kernel_backend": "xla"})
    fp_b = fingerprint({"kernel_backend": "pallas"})
    store_mock(cache, "prog.a", fp_a)
    store_mock(cache, "prog.a", fp_b)
    assert cache.counters()["entries"] == 2
    assert cache.load("prog.a", ("s",), fp_a, deserialize=mock_codec)
    assert cache.load("prog.a", ("s",), fp_b, deserialize=mock_codec)
    assert cache.hits == 2 and cache.misses == 0


def test_aot_cache_wrong_format_meta_degrades(tmp_path):
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint({"m": "tiny"})
    store_mock(cache, "prog.a", fp)
    meta = [p for p in os.listdir(cache.dir) if p.endswith(".json")][0]
    with open(os.path.join(cache.dir, meta), "w") as f:
        json.dump({"format": "something-else-v9"}, f)
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec) is None
    assert cache.errors == 1
    # and a TRUNCATED meta (torn write outside the commit protocol)
    with open(os.path.join(cache.dir, meta), "w") as f:
        f.write('{"format": "reval-ao')
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec) is None
    assert cache.errors == 2


def test_aot_cache_unwritable_dir_disables_stores_never_raises(tmp_path):
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where the cache dir should be")
    cache = AOTCache(str(blocker / "aot"))     # mkdir fails: parent is a file
    assert cache._disabled_store
    assert cache.errors == 1
    fp = fingerprint({"m": "tiny"})
    assert not cache.store("prog.a", ("s",), fp, b"payload", 0.1)
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec) is None
    # the serving path survives: counters, gauges, GC all no-op cleanly
    assert cache.gc() == 0
    assert cache.counters()["entries"] == 0


def test_aot_cache_gc_evicts_lru_until_bound(tmp_path):
    cache = AOTCache(str(tmp_path / "aot"), max_mb=2048)
    fp = fingerprint({"m": "tiny"})
    for i, entry in enumerate(("prog.a", "prog.b", "prog.c")):
        store_mock(cache, entry, fp)
        mtime = time.time() - 300 + i * 100    # distinct LRU stamps
        base = cache._base(entry, ("s",), fp)
        os.utime(base + ".json", (mtime, mtime))
    # a hit refreshes prog.a's stamp: it must survive the GC below
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec)
    evicted = cache.gc(max_mb=0)
    assert evicted >= 2
    names = " ".join(os.listdir(cache.dir))
    assert "prog_b" not in names and "prog_c" not in names


def test_aot_cache_gc_reaps_stale_orphan_payloads(tmp_path):
    """A crash inside the payload-first commit window leaves a ``.bin``
    whose meta never landed: invisible to ``entries()`` but charged
    against the size bound — GC must reap it (after a grace period)
    instead of uselessly evicting the live cache around it."""
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint({"m": "tiny"})
    store_mock(cache, "prog.a", fp)
    orphan = os.path.join(cache.dir, "prog_dead-ffff-0000.bin")
    with open(orphan, "wb") as f:
        f.write(b"x" * 4096)
    fresh_tmp = os.path.join(cache.dir, "prog_live-ffff-0000.bin.tmp")
    with open(fresh_tmp, "wb") as f:
        f.write(b"y")               # a writer mid-commit: must survive
    old = time.time() - 3600
    os.utime(orphan, (old, old))
    cache.gc()                      # generous bound: no LRU eviction...
    assert not os.path.exists(orphan)       # ...but the orphan is gone
    assert os.path.exists(fresh_tmp)        # grace period protects it
    assert cache.counters()["entries"] == 1  # live entry untouched
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec)


def test_aot_cache_gc_covers_jax_xla_subdir(tmp_path):
    """jax's persistent compilation cache under ``<dir>/xla`` is part of
    the directory REVAL_TPU_AOT_CACHE_MAX_MB promises to bound: its
    bytes must count, and GC must reap its (cheaper-to-rebuild) files
    BEFORE evicting AOT entries."""
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint({"m": "tiny"})
    store_mock(cache, "prog.a", fp)
    _, aot_only = cache._usage()
    xla = tmp_path / "aot" / "xla"
    xla.mkdir()
    (xla / "module_big").write_bytes(b"z" * (2 * 1024 * 1024))
    cache._xla_scan = (0.0, 0)      # drop the TTL memo: fresh view
    _, total = cache._usage()
    assert total >= aot_only + 2 * 1024 * 1024      # xla bytes counted
    assert cache.gc(max_mb=1) == 0                  # no AOT entry evicted...
    assert not (xla / "module_big").exists()        # ...the xla file went
    assert cache.counters()["entries"] == 1
    assert cache.load("prog.a", ("s",), fp, deserialize=mock_codec)


def test_template_stats_stay_bounded():
    """The per-template affinity dict rides every snapshot whole — a
    high-diversity workload must not grow it (and the snapshot) without
    bound; the heavy templates survive the fold."""
    from reval_tpu.inference.tpu.engine import (TEMPLATE_STATS_CAP,
                                                bump_template_stats)

    stats: dict = {}
    bump_template_stats(stats, 424242, 1000)     # the heavy hitter
    for tag in range(TEMPLATE_STATS_CAP * 2):
        bump_template_stats(stats, tag)
    assert len(stats) <= TEMPLATE_STATS_CAP
    assert stats[424242] == 1000


def test_restore_template_stats_tolerates_garbage():
    """Keys AND counts come off disk: one corrupt row (non-numeric
    either side) skips that row only — it must never abort a restore
    whose chains already replayed (both engines share this helper)."""
    from reval_tpu.inference.tpu.engine import restore_template_stats

    stats: dict = {}
    restore_template_stats(stats, {"12": 3, "x": 1, "13": None, "14": "2"})
    assert stats == {12: 3, 14: 2}
    restore_template_stats(stats, None)         # absent doc: no-op
    assert stats == {12: 3, 14: 2}


def test_dp_aot_counters_directory_gauges_take_max():
    """dp replicas share ONE cache directory: the merged ``entries``/
    ``bytes`` must describe that directory once, not dp× it, while the
    per-process work counters still sum."""
    from types import SimpleNamespace

    from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine

    rows = [{"enabled": True, "hits": 3, "misses": 1, "entries": 10,
             "bytes": 500, "dir": "/d"},
            {"enabled": True, "hits": 2, "misses": 0, "entries": 10,
             "bytes": 500, "dir": "/d"}]
    reps = [SimpleNamespace(aot_counters=lambda r=r: dict(r)) for r in rows]
    out = DataParallelPagedEngine.aot_counters(
        SimpleNamespace(replicas=reps))
    assert out["hits"] == 5 and out["misses"] == 1
    assert out["entries"] == 10 and out["bytes"] == 500


def test_resolved_kernel_knobs_ride_the_fingerprint(monkeypatch):
    """REVAL_TPU_KERNEL_DOT / REVAL_TPU_FORCE_MOSAIC bind at trace time
    under one backend label — two knob settings must fingerprint (and so
    cache) differently, while the xla formulation (which reads neither)
    stays knob-invariant."""
    from reval_tpu.ops.pallas_attention import resolved_kernel_knobs

    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "xla")
    monkeypatch.setenv("REVAL_TPU_KERNEL_DOT", "wide")
    assert resolved_kernel_knobs() == {"dot_mode": "n/a",
                                       "interpret": "n/a"}
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "pallas")
    wide = resolved_kernel_knobs()
    assert wide["dot_mode"] == "wide"
    monkeypatch.setenv("REVAL_TPU_KERNEL_DOT", "swap")
    swap = resolved_kernel_knobs()
    assert swap["dot_mode"] == "swap"
    assert fingerprint({**{"kernel_backend": "pallas"}, **wide}) \
        != fingerprint({**{"kernel_backend": "pallas"}, **swap})


def test_aot_cache_verify_entry_verdicts(tmp_path):
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint({"m": "tiny"})
    store_mock(cache, "prog.ok", fp)
    store_mock(cache, "prog.bad", fp)
    bad_payload = cache._base("prog.bad", ("s",), fp) + ".bin"
    with open(bad_payload, "wb") as f:
        f.write(b"x")
    verdicts = {row["entry"]: cache.verify_entry(row)
                for row in cache.entries()}
    assert verdicts["prog.ok"] is None
    assert "checksum" in verdicts["prog.bad"]


# ---------------------------------------------------------------------------
# AotJit: the real jax.export round trip + degraded environments
# ---------------------------------------------------------------------------

class _FakeTracked:
    """Minimal TrackedJit surface for wrapper-level tests."""

    def __init__(self, fn, name="t.prog", warmup=8):
        self._fn = fn
        self.name = name
        self.warmup = warmup
        self.calls = 0

    def note_call(self, args, kwargs):
        self.calls += 1
        shapes = tuple(getattr(a, "shape", a) for a in args)
        statics = tuple(sorted(kwargs.items())) if kwargs else ()
        return (shapes, statics)

    @property
    def variants(self):
        return 0

    @property
    def misses(self):
        return 0


def test_aot_jit_real_export_round_trip(tmp_path):
    import jax
    import jax.numpy as jnp

    cache = AOTCache(str(tmp_path / "aot"))
    ctx = {"prog": "double"}
    x = jnp.arange(8, dtype=jnp.float32)

    wrapped = AotJit(_FakeTracked(jax.jit(lambda v: v * 2)), cache, ctx)
    out = wrapped(x)
    assert (out == x * 2).all()
    assert wrapped.fresh_compiles == 1 and cache.misses == 1
    assert cache.counters()["entries"] == 1     # exported + stored

    # a NEW wrapper (new process's view) over the same directory loads
    # the serialized executable: zero fresh compiles, identical output
    wrapped2 = AotJit(_FakeTracked(jax.jit(lambda v: v * 2)), cache, ctx)
    out2 = wrapped2(x)
    assert (out2 == out).all()
    assert wrapped2.fresh_compiles == 0 and cache.hits == 1
    # and the loaded executable serves repeat calls without re-probing
    assert (wrapped2(x) == out).all()
    assert cache.hits == 1


def test_aot_jit_static_args_bake_into_separate_variants(tmp_path):
    import jax
    import jax.numpy as jnp

    cache = AOTCache(str(tmp_path / "aot"))

    def f(v, *, steps):
        return v + steps

    jitted = jax.jit(f, static_argnames=("steps",))
    w1 = AotJit(_FakeTracked(jitted), cache, {"prog": "s"},
                static=("steps",))
    x = jnp.arange(4, dtype=jnp.float32)
    assert (w1(x, steps=2) == x + 2).all()
    assert (w1(x, steps=5) == x + 5).all()
    assert cache.counters()["entries"] == 2     # one per static value
    w2 = AotJit(_FakeTracked(jax.jit(f, static_argnames=("steps",))),
                cache, {"prog": "s"}, static=("steps",))
    # dispatch to the LOADED executable strips the baked static
    assert (w2(x, steps=2) == x + 2).all()
    assert (w2(x, steps=5) == x + 5).all()
    assert w2.fresh_compiles == 0 and cache.hits == 2


def test_aot_jit_canary_reports_unsupported_never_raises(tmp_path):
    """The degraded-env satellite: when the Mosaic canary says kernel
    export is unavailable, the cache reports ``unsupported`` (counted,
    logged ONCE) and the entry serves through the plain tracker — the
    serving path never sees the doomed export."""
    import jax
    import jax.numpy as jnp

    cache = AOTCache(str(tmp_path / "aot"))
    probes = {"n": 0}

    def canary():
        probes["n"] += 1
        return "mosaic lowering unavailable on this host (canary)"

    w = AotJit(_FakeTracked(jax.jit(lambda v: v * 3)), cache,
               {"prog": "k"}, canary=canary)
    x = jnp.arange(4, dtype=jnp.float32)
    assert (w(x) == x * 3).all()
    assert (w(x) == x * 3).all()
    assert cache.unsupported == 1               # counted once
    assert probes["n"] == 1                     # probed once
    assert cache.counters()["entries"] == 0     # nothing stored
    # the shared canary itself returns a stable verdict (None on a chip
    # jax; a named environment gap here) — same probe
    # tests/test_tpu_lowering.py skips its kernel exports on
    verdict = kernel_export_skip()
    assert verdict is None or "jax" in verdict


def test_aot_jit_export_failure_degrades_to_unsupported(tmp_path):
    import jax
    import jax.numpy as jnp

    cache = AOTCache(str(tmp_path / "aot"))

    def impure(v):
        # jax.export rejects host callbacks — a program this build
        # cannot export, without a canary to predict it
        import jax.debug

        jax.debug.callback(lambda *_: None, v)
        return v * 2

    w = AotJit(_FakeTracked(jax.jit(impure)), cache, {"prog": "cb"})
    x = jnp.arange(4, dtype=jnp.float32)
    assert (w(x) == x * 2).all()                # the call itself served
    assert cache.unsupported == 1
    assert cache.counters()["entries"] == 0


# ---------------------------------------------------------------------------
# Warm-state snapshots
# ---------------------------------------------------------------------------

def test_snapshot_write_read_round_trip_atomic(tmp_path):
    path = str(tmp_path / "snap.json")
    state = {"prefix_chains": [[1, 2, 3]], "template_stats": {"9": 4}}
    assert write_snapshot(path, state, unfinished_request_ids=["rid-1"])
    assert not os.path.exists(path + ".tmp")    # atomic: tmp renamed away
    doc = read_snapshot(path)
    assert doc["format"] == SNAP_FORMAT
    assert doc["engine"] == state
    assert doc["unfinished_request_ids"] == ["rid-1"]


def test_snapshot_corrupt_and_garbage_read_cold(tmp_path):
    path = tmp_path / "snap.json"
    assert read_snapshot(str(path)) is None     # absent: silent cold boot
    path.write_text('{"format": "reval-warm-sn')     # truncated
    assert read_snapshot(str(path)) is None
    path.write_text(json.dumps({"format": "wrong-v0", "engine": {}}))
    assert read_snapshot(str(path)) is None
    path.write_text(json.dumps({"format": SNAP_FORMAT, "engine": "nope"}))
    assert read_snapshot(str(path)) is None


def test_snapshot_v2_kv_page_refs_round_trip_v1_accepted_v3_cold(tmp_path):
    """The v2 schema adds disk-tier page refs (kv_tiers.py sidecar).
    Old v1 docs must still warm-boot (chain replay only); an UNKNOWN
    future version must boot cold — never guess at a schema."""
    path = str(tmp_path / "snap.json")
    refs = [{"key": "a" * 64, "file": "a.kvpage",
             "sha256": "b" * 64, "nbytes": 4096}]
    assert write_snapshot(path, {"prefix_chains": [[1, 2]]}, kv_pages=refs)
    doc = read_snapshot(path)
    assert doc["format"] == SNAP_FORMAT == "reval-warm-snapshot-v2"
    assert doc["kv_pages"] == refs
    # no tier store → no kv_pages key at all (v1-shaped doc, v2 format)
    bare = str(tmp_path / "bare.json")
    assert write_snapshot(bare, {"prefix_chains": []})
    assert "kv_pages" not in read_snapshot(bare)

    v1 = {"format": "reval-warm-snapshot-v1",
          "engine": {"prefix_chains": [[7] * 8], "template_stats": {}}}
    (tmp_path / "v1.json").write_text(json.dumps(v1))
    got = read_snapshot(str(tmp_path / "v1.json"))
    assert got is not None and got["engine"] == v1["engine"]

    (tmp_path / "v3.json").write_text(
        json.dumps(dict(v1, format="reval-warm-snapshot-v3")))
    assert read_snapshot(str(tmp_path / "v3.json")) is None


def test_session_fallback_boots_sibling_snapshot_with_tier_refs(tmp_path):
    """Autoscaler warm scale-up: a replica with no (or a corrupt)
    snapshot of its own inherits a SIBLING's — including the v2 disk
    tier refs, attached before rewarm so replayed chains promote real
    KV bytes."""
    from reval_tpu.serving import ContinuousSession, MockStepEngine

    sib = str(tmp_path / "sibling.json")
    refs = [{"key": "a" * 64, "file": "a.kvpage",
             "sha256": "b" * 64, "nbytes": 4096}]
    chain = [3] * 8
    assert write_snapshot(sib, {"prefix_chains": [chain],
                                "template_stats": {}}, kv_pages=refs)
    own = tmp_path / "own.json"
    own.write_text('{"format": "reval-warm-sn')       # corrupt → fallback

    class TierMock(MockStepEngine):
        def __init__(self):
            super().__init__()
            self.attached = None

        def attach_tier_refs(self, refs, dir_path):
            self.attached = (refs, dir_path)
            return len(refs)

    eng = TierMock()
    session = ContinuousSession(eng, snapshot_path=str(own),
                                snapshot_fallback=sib)
    try:
        deadline = time.monotonic() + 10
        while session._warming.is_set() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not session._warming.is_set()
        assert chain in eng._warm_chains            # sibling chains warm
        assert eng.attached == (refs, f"{sib}.pages")
    finally:
        session.close()


def test_rewarm_failed_prefill_rolls_back_chain(monkeypatch):
    """A chain whose replay prefill dies mid-boot must not survive as
    uncommitted (garbage) KV — a later rider would decode against it
    silently wrong — nor stay pinned (unevictable forever).  Same
    rollback contract as the submit path."""
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "xla")
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params

    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                         page_size=128, max_seq_len=512)
    try:
        def boom(*a, **k):
            raise RuntimeError("device fell over mid-replay")

        monkeypatch.setattr(eng, "_prefill_prefix_pages", boom)
        warmed = eng.rewarm({"prefix_chains": [list(range(1, 129))],
                             "template_stats": {}})
        assert warmed == 0
        assert eng.prefix_cache.nodes == 0          # nothing survived
        assert eng.prefix_cache.pinned_pages == 0   # nothing left pinned
        assert eng.stats.prefix_hit_tokens == 0     # credit rolled back
    finally:
        eng.close()


def test_close_without_start_preserves_previous_snapshot(tmp_path):
    """A session whose driver never ran (autostart=False, or a boot
    that died before start()) has a COLD engine — its close() must not
    clobber the previous process's good snapshot with an empty one."""
    from reval_tpu.serving import ContinuousSession, MockStepEngine

    snap = str(tmp_path / "snap.json")
    good = {"prefix_chains": [[7] * 128], "template_stats": {"5": 2}}
    assert write_snapshot(snap, good)
    session = ContinuousSession(MockStepEngine(), autostart=False,
                                snapshot_path=snap)
    session.close()
    doc = read_snapshot(snap)
    assert doc is not None and doc["engine"] == good


def test_snapshot_unwritable_dir_degrades(tmp_path):
    blocker = tmp_path / "f"
    blocker.write_text("file, not dir")
    assert not write_snapshot(str(blocker / "deep" / "snap.json"),
                              {"prefix_chains": []})


def test_corrupt_snapshot_boots_cold_server_still_serves(tmp_path):
    """A truncated/garbage snapshot file must boot a COLD engine with a
    warning event — never wedge startup behind ``warming``."""
    snap = tmp_path / "snap.json"
    snap.write_text('{"format": "reval-warm-snapshot-v1", "engine": {"pre')
    srv = serve_config({"mock": True,
                        "snapshot_path": str(snap)}, port=0).start()
    try:
        deadline = time.monotonic() + 10
        status = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}/readyz",
                        timeout=5) as r:
                    status = json.loads(r.read())["status"]
                    break
            except urllib.error.HTTPError:
                time.sleep(0.02)
        assert status == "ready"
        body = json.dumps({"prompt": "p", "max_tokens": 8}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert out["choices"][0]["text"]
    finally:
        srv.shutdown()


def test_double_drain_writes_one_snapshot(tmp_path, monkeypatch):
    import reval_tpu.serving.session as session_mod

    writes = []
    real = session_mod.write_snapshot
    monkeypatch.setattr(session_mod, "write_snapshot",
                        lambda *a, **kw: (writes.append(a[0]),
                                          real(*a, **kw))[1])
    snap = str(tmp_path / "snap.json")
    srv = serve_config({"mock": True, "snapshot_path": snap},
                       port=0).start()
    body = json.dumps({"prompt": "T " * 200, "max_tokens": 8}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=10).read()
    srv.shutdown()
    srv._session.close()                        # drain AGAIN, directly
    srv._session.close()
    assert writes == [snap]                     # exactly one write
    doc = read_snapshot(snap)
    assert doc is not None and len(doc["engine"]["prefix_chains"]) >= 1
    assert not os.path.exists(snap + ".tmp")


# ---------------------------------------------------------------------------
# Warming readiness: server, client handshake, router poller
# ---------------------------------------------------------------------------

def _warm_server(tmp_path, rewarm_s=0.4, port=0, **cfg):
    """A mock server whose boot replays a seeded snapshot slowly enough
    that the ``warming`` window is observable."""
    snap = str(tmp_path / "warm.snap")
    if not os.path.exists(snap):
        write_snapshot(snap, {"prefix_chains": [[7] * 128, [9] * 128],
                              "template_stats": {"1": 2}})
    return serve_config({"mock": True, "snapshot_path": snap,
                         "mock_rewarm_s": rewarm_s, **cfg},
                        port=port).start(), snap


def _poll_readyz_until_ready(port, timeout=15.0):
    """(statuses seen, final body) polling /readyz until 200."""
    seen = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=5) as r:
                body = json.loads(r.read())
                seen.append(body["status"])
                return seen, body
        except urllib.error.HTTPError as exc:
            body = json.loads(exc.read())
            seen.append(body["status"])
            assert exc.headers.get("Retry-After")
            time.sleep(0.03)
        except urllib.error.URLError:
            time.sleep(0.03)
    raise AssertionError(f"never ready; statuses: {seen[-5:]}")


def test_readyz_warming_distinct_from_draining(tmp_path):
    srv, _ = _warm_server(tmp_path)
    try:
        seen, body = _poll_readyz_until_ready(srv.port)
        assert "warming" in seen                # the 503-warming window
        assert seen[-1] == "ready"
        assert body["warming"] is False
        # restart-to-ready observed + warm prefixes counted
        snap = srv._session.engine.stats.registry.snapshot()
        assert snap["histograms"][
            obs_metrics.RESTART_TO_READY]["count"] >= 1
        assert snap["counters"][obs_metrics.RESTART_WARM_PREFIXES] == 2
        # draining is a DIFFERENT status on the same route
        srv._draining.set()
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/readyz", timeout=5)
        assert json.loads(err.value.read())["status"] == "draining"
        srv._draining.clear()
    finally:
        srv.shutdown()


def test_client_handshake_waits_through_warming(tmp_path):
    srv, _ = _warm_server(tmp_path, rewarm_s=0.3)
    try:
        client = HTTPClientBackend(model_id="m", port=srv.port, temp=0.0,
                                   prompt_type="direct",
                                   wait_for_server_s=20, retry=FAST_RETRY)
        assert client.infer_one("hello")        # arrived after the warm-up
    finally:
        srv.shutdown()


def test_router_poller_polls_through_warming_no_strikes(tmp_path):
    srv, _ = _warm_server(tmp_path, rewarm_s=0.4)
    router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                         health_interval_s=0.05, eject_fails=2).start()
    try:
        # while warming: alive (no strikes, never ejected), not ready
        deadline = time.monotonic() + 10
        saw_warming = False
        while time.monotonic() < deadline:
            rep = router.statusz()["replicas"][0]
            assert rep["state"] != "ejected"
            assert rep["poll_fails"] == 0
            if rep.get("warming"):
                saw_warming = True
            if rep["ready"]:
                break
            time.sleep(0.03)
        assert saw_warming
        assert router.readiness()["ready"]
    finally:
        router.shutdown()
        srv.shutdown()


# ---------------------------------------------------------------------------
# Crash-loop supervisor
# ---------------------------------------------------------------------------

class _FakeChild:
    def __init__(self, rc):
        self._rc = rc
        self.pid = 4242

    def wait(self):
        return self._rc


def _script_supervisor(codes, tmp_path, **kw):
    """A supervisor whose children exit with ``codes`` in order."""
    queue = list(codes)
    sleeps = []
    sup = Supervisor(spawn=lambda: _FakeChild(queue.pop(0)),
                     postmortem_dir=str(tmp_path / "pm"),
                     sleep=sleeps.append, **kw)
    return sup, sleeps


def test_supervisor_respawns_with_backoff_then_graceful_stop(tmp_path):
    sup, sleeps = _script_supervisor([1, 1, 1, 0], tmp_path,
                                     max_deaths=5, window_s=60.0,
                                     base_backoff_s=0.25)
    assert sup.run() == 0
    assert sup.state == "stopped"
    assert sup.respawns == 4
    assert len(sleeps) == 3                     # one backoff per death
    assert sleeps[0] < sleeps[1] < sleeps[2]    # exponential schedule
    # one postmortem bundle per death
    bundles = [p for p in os.listdir(tmp_path / "pm")
               if p.startswith("postmortem-")]
    assert len(bundles) == 3
    with open(tmp_path / "pm" / bundles[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "supervisor_child_death"
    assert doc["exit_code"] == 1


def test_supervisor_goes_sticky_failed_after_rapid_death_budget(tmp_path):
    sup, _ = _script_supervisor([1] * 10, tmp_path, max_deaths=3,
                                window_s=60.0, base_backoff_s=0.01)
    assert sup.run() == 1                       # stopped respawning
    assert sup.state == "sticky_failed"
    assert sup.respawns == 3                    # never flapped past budget
    snap = sup._obs.snapshot()["counters"]
    assert snap[obs_metrics.RESTART_DEATHS] == 3
    assert snap[obs_metrics.RESTART_RESPAWNS] == 3


def test_supervisor_deaths_age_out_of_the_window(tmp_path):
    clock = {"t": 0.0}

    def tick():
        clock["t"] += 100.0                     # every observation is
        return clock["t"]                       # 100 s after the last

    sup = Supervisor(spawn=lambda: _FakeChild(1), max_deaths=2,
                     window_s=60.0, base_backoff_s=0.01,
                     postmortem_dir=str(tmp_path / "pm"),
                     clock=tick, sleep=lambda s: None)
    done = {}

    def run():
        done["rc"] = sup.run()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and sup.respawns < 8:
        time.sleep(0.01)
    assert sup.respawns >= 8                    # far past max_deaths=2:
    sup.stop()                                  # deaths aged out each time
    thread.join(timeout=10)
    assert done["rc"] == 0 and sup.state == "stopped"


def test_supervisor_graceful_child_exit_is_not_respawned(tmp_path):
    sup, sleeps = _script_supervisor([0], tmp_path, max_deaths=3)
    assert sup.run() == 0
    assert sup.respawns == 1 and sleeps == []


def test_serve_supervise_cli_runs_child_to_graceful_exit():
    """`serve --supervise --mock --smoke N`: the child runs the smoke
    and exits 0; the supervisor must treat that as a deliberate stop
    (exit 0, no respawn loop)."""
    r = subprocess.run(
        [sys.executable, "-m", "reval_tpu", "serve", "--supervise",
         "--mock", "--smoke", "2"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[supervise]" in r.stdout
    assert "--supervise" not in r.stdout.split("[supervise]")[1].split(
        "\n")[0].replace("respawning `", "")   # child argv drops the flag


# ---------------------------------------------------------------------------
# tools/aot_cache.py CLI
# ---------------------------------------------------------------------------

def test_aot_cache_cli_ls_verify_gc_json_round_trip(tmp_path):
    cache = AOTCache(str(tmp_path / "aot"))
    fp = fingerprint(runtime_context(engine="cli-test"))
    store_mock(cache, "prog.a", fp, compile_s=1.5)
    store_mock(cache, "prog.b", fp, compile_s=2.5)

    def run_cli(*argv):
        return subprocess.run(
            [sys.executable, "tools/aot_cache.py", *argv,
             "--dir", str(tmp_path / "aot"), "--json"],
            capture_output=True, text=True, timeout=120, cwd=REPO)

    r = run_cli("ls")
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["command"] == "ls" and len(doc["entries"]) == 2
    assert {e["entry"] for e in doc["entries"]} == {"prog.a", "prog.b"}
    assert all(e["payload_bytes"] > 0 for e in doc["entries"])

    r = run_cli("verify")
    assert r.returncode == 0
    assert json.loads(r.stdout)["broken"] == 0
    # corrupt one payload: verify must exit 1 and name the problem
    bad = cache._base("prog.a", ("s",), fp) + ".bin"
    with open(bad, "wb") as f:
        f.write(b"zzz")
    r = run_cli("verify")
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["broken"] == 1
    broken = [e for e in doc["entries"] if not e["ok"]][0]
    assert broken["entry"] == "prog.a" and "checksum" in broken["problem"]

    r = run_cli("gc", "--max-mb", "0")
    assert r.returncode == 0
    doc = json.loads(r.stdout)
    assert doc["evicted"] == 2 and doc["entries_left"] == 0

    # no directory at all → usage error, not a crash
    r = subprocess.run(
        [sys.executable, "tools/aot_cache.py", "ls", "--dir",
         str(tmp_path / "nope")],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 2


# ---------------------------------------------------------------------------
# The rolling-restart drill (the ISSUE 10 acceptance scenario)
# ---------------------------------------------------------------------------

def make_replica(port=0, **cfg):
    base = {"mock": True, "mock_echo": True}
    base.update(cfg)
    return serve_config(base, port=port).start()


def make_router(servers, **kw):
    kw.setdefault("health_interval_s", 0.05)
    kw.setdefault("cooldown_s", 0.3)
    kw.setdefault("eject_fails", 2)
    return FleetRouter([f"127.0.0.1:{s.port}" for s in servers],
                       port=0, **kw).start()


def wait_router_ready(router, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.readiness()["ready"]:
            return
        time.sleep(0.02)
    raise AssertionError("router never became ready")


def hard_kill(server) -> None:
    server._httpd.shutdown()
    server._httpd.server_close()


def admin(router, route, replica_id):
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}{route}",
        data=json.dumps({"replica": replica_id}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def post_completion(port, prompt, max_tokens=32):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps({"prompt": prompt,
                         "max_tokens": max_tokens}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def post_router(router, prompt, max_tokens=32):
    return post_completion(router.port, prompt, max_tokens)


def replica_states(router):
    return {r["id"]: r for r in router.statusz()["replicas"]}


def _run_fleet(results_dir, port, repeats=2, resume=False):
    from reval_tpu.fleet import FleetRunner

    backend = HTTPClientBackend(model_id="drill", port=port, temp=0.0,
                                prompt_type="direct", wait_for_server_s=30,
                                retry=FAST_RETRY)
    fleet = FleetRunner(dataset="humaneval", prompt_type="direct",
                        repeats=repeats, backend=backend,
                        results_dir=str(results_dir), progress=False,
                        run_consistency=False, max_items=2,
                        tasks=("coverage", "path"), resume=resume)
    try:
        return fleet.run()
    finally:
        backend.close()


def _task_logs(results_dir):
    logs = {}
    for task in ("coverage", "path"):
        d = os.path.join(str(results_dir), f"{task}@drill_direct_temp0.0")
        paths = sorted((os.path.join(d, f) for f in os.listdir(d)),
                       key=os.path.getctime)
        logs[task] = [open(p).read() for p in paths]
    return logs


def test_rolling_restart_drill(tmp_path, monkeypatch):
    """Drain A → graceful stop (snapshot) → supervised restart on the
    same port → /readyz flips via ``warming`` with AOT hits > 0 and
    ZERO fresh compiles → router rejoin → hard-kill B mid-fleet → zero
    lost prompts, task logs byte-identical to a no-restart run."""
    monkeypatch.setenv("REVAL_TPU_AOT_CACHE_DIR", str(tmp_path / "aot"))

    # -- baseline: no restart, same router topology ----------------------
    base_srv = make_replica(snapshot_path=str(tmp_path / "base.snap"))
    base_router = make_router([base_srv])
    wait_router_ready(base_router)
    try:
        base_result = _run_fleet(tmp_path / "base", base_router.port)
    finally:
        base_router.shutdown()
        base_srv.shutdown()
    assert "lost_prompts" not in base_result
    # the baseline replica's cold boot populated the shared AOT dir
    assert base_srv._session.engine.aot_counters()["fresh_compiles"] == 2

    # -- the drill topology ----------------------------------------------
    snap_a = str(tmp_path / "a.snap")
    rep_a = make_replica(snapshot_path=snap_a)
    rep_b = make_replica(snapshot_path=str(tmp_path / "b.snap"))
    # every later boot hits the baseline's cached programs
    assert rep_a._session.engine.aot_counters()["fresh_compiles"] == 0
    router = make_router([rep_a, rep_b])
    wait_router_ready(router)
    a_id = f"127.0.0.1:{rep_a.port}"
    supervisor = sup_thread = None
    restarted: dict = {}
    killed: dict = {}
    try:
        # seed A with traffic so its snapshot carries warm state —
        # DIRECTLY, not through the router: the ring's template
        # placement depends on the replicas' ephemeral ports, so a
        # routed seed can land every template on B and leave A's
        # snapshot chainless (no chains → nothing to replay → the
        # warming window below is too short to observe)
        post_completion(rep_a.port, TEMPLATE_A + "seed probe")
        post_router(router, TEMPLATE_B + "seed probe")
        assert rep_a._session.engine.warm_state()["prefix_chains"]

        # 1. drain A through the router, then stop it gracefully: the
        # drain writes the warm-state snapshot
        assert admin(router, "/admin/drain", a_id)[
            "replica"]["state"] == "draining"
        rep_a.shutdown()
        assert os.path.exists(snap_a)
        # 2. rejoin the (now dead) replica: the health poller must see
        # the corpse and eject it — the state the half-open recovery
        # path rejoins from
        admin(router, "/admin/rejoin", a_id)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if replica_states(router)[a_id]["state"] == "ejected":
                break
            time.sleep(0.02)
        assert replica_states(router)[a_id]["state"] == "ejected"

        # 3. supervised restart on the SAME port, warm: the supervisor's
        # first spawn IS the restart
        class _ReplicaChild:
            def __init__(self):
                self.server = make_replica(port=rep_a.port,
                                           snapshot_path=snap_a,
                                           mock_rewarm_s=0.6)
                restarted["server"] = self.server
                self.pid = os.getpid()
                self.dead = threading.Event()

            def wait(self):
                self.dead.wait()
                return 0

        supervisor = Supervisor(spawn=_ReplicaChild, max_deaths=3,
                                base_backoff_s=0.01,
                                postmortem_dir=str(tmp_path / "pm"))
        sup_thread = threading.Thread(target=supervisor.run, daemon=True)
        sup_thread.start()
        deadline = time.monotonic() + 10
        while "server" not in restarted and time.monotonic() < deadline:
            time.sleep(0.01)
        assert supervisor.respawns == 1

        # 4. /readyz flips via WARMING, with AOT hits and zero fresh
        # compiles of the already-cached entries
        seen, _ = _poll_readyz_until_ready(rep_a.port)
        assert "warming" in seen and seen[-1] == "ready"
        eng = restarted["server"]._session.engine
        aot = eng.aot_counters()
        assert aot["hits"] >= 2, aot
        assert aot["fresh_compiles"] == 0, aot
        reg = eng.stats.registry.snapshot()
        assert reg["histograms"][obs_metrics.RESTART_TO_READY]["count"] >= 1
        assert reg["counters"][obs_metrics.RESTART_WARM_PREFIXES] >= 1

        # 5. the router rejoins the restarted replica (clean health poll
        # out of ejection — the half-open recovery family) and routes
        # through it again
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            rep = replica_states(router)[a_id]
            if rep["state"] == "healthy" and rep["ready"]:
                break
            time.sleep(0.03)
        rep = replica_states(router)[a_id]
        assert rep["state"] == "healthy" and rep["ready"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{router.port}/metrics", timeout=10) as r:
            from reval_tpu.obs.metrics import parse_prometheus

            samples = parse_prometheus(r.read().decode())
        assert samples[obs_metrics.ROUTER_EJECTIONS] >= 1
        assert samples[obs_metrics.ROUTER_RECOVERIES] >= 1
        # the federation carries the fleet's aot/restart counters too
        assert samples[obs_metrics.AOT_HITS] >= 2
        assert samples[
            obs_metrics.RESTART_TO_READY + "_count"] >= 1

        # 6. hard-kill the second replica mid-fleet: client retry +
        # router failover must finish with zero lost prompts.  "Second"
        # means whichever live replica the fleet's affinity actually
        # lands traffic on — killing an idle replica would test nothing
        live = {f"127.0.0.1:{rep_b.port}": rep_b,
                f"127.0.0.1:{rep_a.port}": restarted["server"]}
        before = {rid: srv._session.engine.stats.prompts
                  for rid, srv in live.items()}

        def assassin():
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                for rid, srv in live.items():
                    if srv._session.engine.stats.prompts > before[rid]:
                        hard_kill(srv)
                        killed["id"] = rid
                        return
                time.sleep(0.002)

        hit = threading.Thread(target=assassin)
        hit.start()
        drill_result = _run_fleet(tmp_path / "drill", router.port)
        hit.join(timeout=60)
        assert "lost_prompts" not in drill_result

        # byte-identical task logs vs the no-restart baseline (echo-mode
        # responses are prompt-determined, so this is a real check)
        assert _task_logs(tmp_path / "drill") == _task_logs(
            tmp_path / "base")
        assert drill_result["repeats"] == base_result["repeats"]
    finally:
        router.shutdown()
        if supervisor is not None:
            supervisor.stop()
            child = supervisor.child
            if child is not None:
                child.dead.set()
            if sup_thread is not None:
                sup_thread.join(timeout=10)
        if ("server" in restarted
                and killed.get("id") != f"127.0.0.1:{rep_a.port}"):
            hard_kill(restarted["server"])
        if killed.get("id") != f"127.0.0.1:{rep_b.port}":
            rep_b.shutdown()
    assert supervisor.state == "stopped"
    assert killed, "the assassin never fired — the drill tested nothing"


# ---------------------------------------------------------------------------
# The real paged engine (slow tier): jax.export round trip + warm session
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_paged_engine_aot_and_snapshot_round_trip(tmp_path, monkeypatch):
    """The real thing, tiny scale: a paged engine under a serving
    session exports its compiled programs and snapshots its prefix tree
    at drain; the next engine+session boots with ZERO fresh compiles
    (all programs deserialized), replays the tree through real prefill,
    and produces bit-identical greedy output."""
    monkeypatch.setenv("REVAL_TPU_AOT_CACHE_DIR", str(tmp_path / "aot"))
    # pin the xla decode kernel: this host's Mosaic lowering cannot
    # export the Pallas kernels (the canary would report unsupported)
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "xla")
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params
    from reval_tpu.serving import ContinuousSession

    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    snap = str(tmp_path / "snap.json")
    prompts = ["def add(a, b):\n    return a + b\n" * 8, "x = 1"]

    def build():
        return PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                              page_size=128, max_seq_len=512)

    e1 = build()
    s1 = ContinuousSession(e1, snapshot_path=snap)
    out1 = s1.submit(prompts, max_new_tokens=8).result()
    aot1 = e1.aot_counters()
    assert aot1["fresh_compiles"] >= 3 and aot1["unsupported"] == 0
    s1.close()
    e1.close()
    doc = read_snapshot(snap)
    assert doc is not None and doc["engine"]["prefix_chains"]

    e2 = build()
    s2 = ContinuousSession(e2, snapshot_path=snap)
    deadline = time.monotonic() + 60
    while s2.readiness()["warming"] and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not s2.readiness()["warming"]
    # the warm restore itself already loaded the prefill/commit programs
    aot2 = e2.aot_counters()
    assert aot2["fresh_compiles"] == 0, aot2    # every program from disk
    assert aot2["hits"] >= 2 and aot2["compile_s_saved"] > 0
    reg = e2.stats.registry.snapshot()
    assert reg["counters"][obs_metrics.RESTART_WARM_PREFIXES] >= 1
    out2 = s2.submit(prompts, max_new_tokens=8).result()
    assert out2 == out1                 # bit-identical via deserialized
    aot2 = e2.aot_counters()
    assert aot2["fresh_compiles"] == 0, aot2    # decode chunk cached too
    assert aot2["hits"] >= 3
    assert e2.stats.prefix_hit_tokens > 0       # the replayed tree serves
    s2.close()
    e2.close()
