"""Cross-request continuous batching (serving/session.py): concurrent
submissions join one live decode batch — the vLLM api_server semantics the
reference's batch_run.py (4 concurrent clients) relies on."""

import json as _json
import threading
import urllib.request

import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params
from reval_tpu.serving import ContinuousSession, EngineServer

PAGE = 128

PROMPTS = [
    "def add(a, b):\n    return a + b\nassert add(",
    "x = 1",
    "for i in range(10):\n    print(i)",
    "y = [k * k for k in range(5)]",
]


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    return cfg, params


def make_engine(tiny, slots=4, prefix_sharing=False):
    cfg, params = tiny
    return PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=slots,
                          page_size=PAGE, max_seq_len=512,
                          prefix_sharing=prefix_sharing)


def test_concurrent_submissions_match_serial_greedy(tiny):
    """Four submissions entering one live batch produce exactly the
    serial greedy outputs, each handle resolving to its own prompts."""
    eng = make_engine(tiny)
    try:
        session = ContinuousSession(eng, autostart=False)
        handles = [session.submit([p], max_new_tokens=12, temperature=0.0)
                   for p in PROMPTS]
        session.start()
        got = [h.result(timeout=300)[0] for h in handles]
        session.close()
        want = eng.generate(PROMPTS, max_new_tokens=12, temperature=0.0)
        assert got == want
    finally:
        eng.close()


def test_fused_admission_shares_decode_chunks(tiny):
    """All-before-start submissions admit as ONE wave: the session spends
    no more decode chunks than the engine's own fused batch call — the
    whole point versus round-2's serialised server (4 clients would have
    cost ~4x the chunks)."""
    eng = make_engine(tiny)
    try:
        session = ContinuousSession(eng, autostart=False)
        handles = [session.submit([p], max_new_tokens=16, temperature=0.0)
                   for p in PROMPTS]
        eng.stats.decode_chunks = 0
        session.start()
        for h in handles:
            h.result(timeout=300)
        session.close()
        fused_chunks = eng.stats.decode_chunks

        eng.stats.decode_chunks = 0
        eng.generate(PROMPTS, max_new_tokens=16, temperature=0.0)
        batch_chunks = eng.stats.decode_chunks
        assert fused_chunks <= batch_chunks + 1, (fused_chunks, batch_chunks)

        eng.stats.decode_chunks = 0
        for p in PROMPTS:
            eng.generate([p], max_new_tokens=16, temperature=0.0)
        serial_chunks = eng.stats.decode_chunks
        assert fused_chunks < serial_chunks, (fused_chunks, serial_chunks)
    finally:
        eng.close()


def test_midflight_admission_overlaps(tiny):
    """A request submitted while another is mid-decode joins the live
    batch (fewer total chunks than running the two serially) and still
    returns the exact serial greedy text."""
    eng = make_engine(tiny, slots=2)
    try:
        serial = [eng.generate([p], max_new_tokens=48, temperature=0.0)[0]
                  for p in PROMPTS[:2]]
        chunks_serial = eng.stats.decode_chunks

        eng.stats.decode_chunks = 0
        session = ContinuousSession(eng)
        a_started = threading.Event()
        h_a = session.submit([PROMPTS[0]], max_new_tokens=48, temperature=0.0,
                             on_progress=lambda i, t: a_started.set())
        assert a_started.wait(timeout=300)
        h_b = session.submit([PROMPTS[1]], max_new_tokens=48, temperature=0.0)
        got = [h_a.result(timeout=300)[0], h_b.result(timeout=300)[0]]
        session.close()
        assert got == serial
        assert eng.stats.decode_chunks < chunks_serial
    finally:
        eng.close()


def test_mixed_temperature_one_batch(tiny):
    """Greedy and sampled requests share a decode chunk: per-slot
    temperature keeps the greedy request exactly greedy."""
    eng = make_engine(tiny)
    try:
        want = eng.generate([PROMPTS[0]], max_new_tokens=12,
                            temperature=0.0)[0]
        session = ContinuousSession(eng, autostart=False)
        h_greedy = session.submit([PROMPTS[0]], max_new_tokens=12,
                                  temperature=0.0)
        h_hot = session.submit([PROMPTS[2]], max_new_tokens=12,
                               temperature=1.0)
        session.start()
        assert h_greedy.result(timeout=300)[0] == want
        h_hot.result(timeout=300)     # completes without fault
        session.close()
    finally:
        eng.close()


def test_oversized_request_fails_only_itself(tiny):
    """A request whose token budget cannot ever fit is rejected AT SUBMIT
    (a client error — the server maps it to 400); the session keeps
    serving others."""
    eng = make_engine(tiny)
    try:
        session = ContinuousSession(eng)
        with pytest.raises(ValueError):
            session.submit(["x"], max_new_tokens=10_000, temperature=0.0)
        ok = session.submit([PROMPTS[1]], max_new_tokens=8, temperature=0.0)
        assert isinstance(ok.result(timeout=300)[0], str)
        session.close()
    finally:
        eng.close()


def test_pool_exceeding_request_fails_only_its_submission(tiny):
    """A request larger than the page pool is rejected by the native
    scheduler at submit (runtime.cpp guards total > num_pages-1, so the
    FCFS queue can never deadlock on it); only its own handle errors and
    requests behind it still complete."""
    cfg, params = tiny
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                         page_size=PAGE, max_seq_len=512, num_pages=3,
                         prefix_sharing=False)
    try:
        session = ContinuousSession(eng, autostart=False)
        # needs 3+ pages > the 2 usable (1 is the trash page)
        big = session.submit([PROMPTS[0]], max_new_tokens=300,
                             temperature=0.0)
        small = session.submit([PROMPTS[1]], max_new_tokens=8,
                               temperature=0.0)
        session.start()
        assert isinstance(small.result(timeout=300)[0], str)
        with pytest.raises(RuntimeError, match="exceeds"):
            big.result(timeout=300)
        session.close()
    finally:
        eng.close()


def test_dp_work_stealing_balances_skewed_prompts(tiny):
    """Adversarially skewed prompt lengths (4 huge + 12 tiny, huge ones
    at the even indices round-robin would have dumped on one replica)
    spread across replicas via the shared work queue: per-replica prefill
    token counts stay within 2x of each other, and outputs still match
    the static engine exactly."""
    import jax

    from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine
    from reval_tpu.inference.tpu.engine import TPUEngine

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    cfg, params = tiny
    long_p = "def f():\n" + "    x += 1\n" * 40       # ~370 tokens
    short_p = ["x = %d" % i for i in range(12)]
    prompts = [long_p + f"# {i}\n" if i % 4 == 0 else short_p[i - i // 4 - 1]
               for i in range(16)]
    static = TPUEngine(params, cfg, ByteTokenizer(), batch_size=4,
                       max_seq_len=512)
    want = static.generate(prompts, max_new_tokens=8, temperature=0.0)
    dpp = DataParallelPagedEngine(params, cfg, ByteTokenizer(), dp_size=2,
                                  tp_size=1, max_slots=2, page_size=PAGE,
                                  max_seq_len=512, prefix_sharing=False)
    try:
        got = dpp.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == want
        loads = [rep.stats.prefill_tokens for rep in dpp.replicas]
        assert min(loads) > 0, loads
        assert max(loads) / min(loads) < 2.0, loads
    finally:
        dpp.close()


def test_dp_prefix_sharing_rides_work_stealing(tiny):
    """Few-shot-template prompts (shared 2-page prefix) through the dp
    work queue: each replica's radix prefix cache prefills the template
    once (on its first pull) and every later pulled prompt rides the
    cached pages, token-identical to the static engine."""
    import jax

    from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine
    from reval_tpu.inference.tpu.engine import TPUEngine

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    cfg, params = tiny
    template = "# few shot\n" + "def ex%d():\n    pass\n" % 7 * 20   # > 2 pages
    prompts = [template + f"\ndef target_{i}(x):\n    return" for i in range(6)]
    static = TPUEngine(params, cfg, ByteTokenizer(), batch_size=2,
                       max_seq_len=1024)
    want = static.generate(prompts, max_new_tokens=8, temperature=0.0)
    dpp = DataParallelPagedEngine(params, cfg, ByteTokenizer(), dp_size=2,
                                  tp_size=1, max_slots=2, page_size=PAGE,
                                  max_seq_len=1024, prefix_sharing=True)
    try:
        got = dpp.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == want
        # the template really was prefilled once per replica, not per row:
        # total prefill tokens ~= 2 * prefix + sum(own suffixes), far less
        # than 6 full prompts
        full = sum(len(ByteTokenizer().encode(p)) for p in prompts)
        assert dpp.stats.prefill_tokens < full * 0.8
    finally:
        dpp.close()


def test_server_concurrent_posts_share_batch(tiny):
    """Four concurrent HTTP clients (the reference batch_run.py shape)
    are admitted into one live batch behind the server."""
    eng = make_engine(tiny)
    try:
        serial = [eng.generate([p], max_new_tokens=16, temperature=0.0)[0]
                  for p in PROMPTS]
        chunks_serial = eng.stats.decode_chunks

        eng.stats.decode_chunks = 0
        session = ContinuousSession(eng)
        srv = EngineServer(session.generate_fn(), model_id="tiny", port=0,
                           serialize=False).start()
        url = f"http://127.0.0.1:{srv.port}/v1/completions"
        results: dict[int, str] = {}
        errors: list[Exception] = []

        def post(i: int) -> None:
            try:
                body = _json.dumps({"prompt": PROMPTS[i], "max_tokens": 16,
                                    "temperature": 0.0}).encode()
                with urllib.request.urlopen(
                        urllib.request.Request(
                            url, data=body,
                            headers={"Content-Type": "application/json"}),
                        timeout=300) as resp:
                    results[i] = _json.loads(resp.read())["choices"][0]["text"]
            except Exception as exc:        # pragma: no cover - surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        srv.shutdown()
        assert not errors, errors
        assert [results[i] for i in range(len(PROMPTS))] == serial
        # the four posts overlapped on the chip rather than queueing
        assert eng.stats.decode_chunks < chunks_serial
    finally:
        eng.close()


def test_multi_session_routes_across_replicas(tiny):
    """Serve-mode dp: concurrent submissions spread over replica
    sessions (both replicas do work), results match serial greedy."""
    import jax

    from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine
    from reval_tpu.serving import MultiSession

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    cfg, params = tiny
    dpp = DataParallelPagedEngine(params, cfg, ByteTokenizer(), dp_size=2,
                                  tp_size=1, max_slots=2, page_size=PAGE,
                                  max_seq_len=512, prefix_sharing=False)
    try:
        serial = [dpp.replicas[0].generate([p], max_new_tokens=12,
                                           temperature=0.0)[0]
                  for p in PROMPTS]
        ms = MultiSession(dpp.replicas)
        handles = [ms.submit([p], max_new_tokens=12, temperature=0.0)
                   for p in PROMPTS]
        got = [h.result(timeout=300)[0] for h in handles]
        ms.close()
        assert got == serial
        # least-loaded routing alternated while all four were outstanding
        assert all(rep.stats.prompts > 0 for rep in dpp.replicas), \
            [rep.stats.prompts for rep in dpp.replicas]
    finally:
        dpp.close()


def test_multi_session_load_releases_on_resolve(tiny):
    import jax

    from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine
    from reval_tpu.serving import MultiSession

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    cfg, params = tiny
    dpp = DataParallelPagedEngine(params, cfg, ByteTokenizer(), dp_size=2,
                                  tp_size=1, max_slots=2, page_size=PAGE,
                                  max_seq_len=512, prefix_sharing=False)
    try:
        ms = MultiSession(dpp.replicas)
        hs = [ms.submit([p], max_new_tokens=8, temperature=0.0)
              for p in PROMPTS]
        for h in hs:
            h.result(timeout=300)
        assert ms._load == [0, 0]       # every weight released
        ms.close()
    finally:
        dpp.close()
