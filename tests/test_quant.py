"""Weight-only int8 quantization (models/quant.py): quantization error
bounds, forward-pass parity, engine integration, sharding rules, and the
``dtype="int8"`` checkpoint-loading path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.models import (
    ModelConfig,
    init_random_params,
    is_quantized,
    logits_for_tokens,
    quantize_params,
)
from reval_tpu.models.quant import MATMUL_WEIGHTS, _quantize_leaf


def small_cfg(**kw):
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16)
    base.update(kw)
    return ModelConfig(**base)


def test_quantize_leaf_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    q, s = _quantize_leaf(w)
    assert q.dtype == jnp.int8 and s.shape == (48,)
    deq = q.astype(jnp.float32) * s[None, :]
    # symmetric per-channel: max error is half a quantization step
    step = np.asarray(s)[None, :]
    assert np.abs(np.asarray(deq - w)).max() <= 0.5 * step.max() + 1e-6


def test_zero_column_is_stable():
    w = jnp.zeros((8, 4), jnp.float32).at[:, 1].set(1.0)
    q, s = _quantize_leaf(w)
    deq = np.asarray(q.astype(jnp.float32) * s[None, :])
    assert np.isfinite(deq).all()
    np.testing.assert_allclose(deq[:, 0], 0.0)
    np.testing.assert_allclose(deq[:, 1], 1.0, rtol=1e-2)


def test_quantized_tree_shape_and_flags():
    cfg = small_cfg(tie_word_embeddings=False)
    params = init_random_params(cfg, seed=0, dtype="float32")
    qp = quantize_params(params)
    assert is_quantized(qp) and not is_quantized(params)
    for name in MATMUL_WEIGHTS:
        if name == "lm_head":
            assert qp["lm_head"].dtype == jnp.int8
            assert qp["lm_head_scale"].shape == (cfg.vocab_size,)
        elif name in qp["layers"]:
            assert qp["layers"][name].dtype == jnp.int8
            scale = qp["layers"][name + "_scale"]
            assert scale.shape == (cfg.num_layers,
                                   qp["layers"][name].shape[-1])
    # embedding and norms untouched
    assert qp["embed"].dtype == params["embed"].dtype
    assert qp["layers"]["attn_norm_w"].dtype == jnp.float32


@pytest.mark.parametrize("family_kw", [
    {},                                                     # llama
    {"family": "starcoder2", "use_layernorm": True, "mlp_gated": False,
     "attention_bias": True, "mlp_bias": True,
     "hidden_act": "gelu_pytorch_tanh"},
])
def test_forward_parity_with_float_weights(family_kw):
    """Quantized logits track the float model: same argmax on most
    positions and small absolute drift (weight-only int8 regime)."""
    cfg = small_cfg(**family_kw)
    params = init_random_params(cfg, seed=1, dtype="float32")
    qp = quantize_params(params)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 24)), jnp.int32)
    ref = np.asarray(logits_for_tokens(params, cfg, tokens))
    got = np.asarray(logits_for_tokens(qp, cfg, tokens))
    assert got.shape == ref.shape
    # int8 weight noise is small relative to logit scale
    denom = np.abs(ref).max()
    assert np.abs(got - ref).max() / denom < 0.15
    agree = (got.argmax(-1) == ref.argmax(-1)).mean()
    assert agree > 0.85, f"argmax agreement {agree}"


def test_paged_engine_generates_with_quantized_params():
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

    cfg = small_cfg()
    params = quantize_params(init_random_params(cfg, seed=2, dtype="float32"))
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                        page_size=128, max_seq_len=512)
    outs = eng.generate(["def f():", "x ="], max_new_tokens=8,
                        temperature=0.0)
    eng.close()
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_sharding_specs_cover_scales():
    from jax.sharding import Mesh, PartitionSpec as P

    from reval_tpu.parallel.sharding import param_specs

    cfg = small_cfg(tie_word_embeddings=False)
    params = quantize_params(init_random_params(cfg, seed=3, dtype="float32"))
    devices = np.array(jax.devices()[:4]).reshape(1, 4)
    mesh = Mesh(devices, ("dp", "tp"))
    specs = param_specs(params, cfg, mesh)
    layers = specs["layers"]
    # out-feature-sharded weights shard their scale; partial-sum weights
    # replicate it; fallback keeps weight and scale consistent
    assert layers["q_w_scale"] == P(None, "tp")
    assert layers["o_w_scale"] == P()
    assert specs["lm_head_scale"] == P("tp")
    # kv heads (2) do not divide tp=4 -> weight AND scale fall back
    assert layers["k_w"] == P()
    assert layers["k_w_scale"] == P()


def test_load_checkpoint_int8(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from reval_tpu.models import load_checkpoint

    torch.manual_seed(0)
    hf = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2)
    LlamaForCausalLM(hf).eval().save_pretrained(tmp_path, safe_serialization=True)
    params, cfg = load_checkpoint(tmp_path, dtype="int8")
    assert is_quantized(params)
    assert params["layers"]["q_w"].dtype == jnp.int8
    assert params["embed"].dtype == jnp.bfloat16      # activations dtype
    assert cfg.dtype == "bfloat16"
    # and the bf16 load of the same checkpoint agrees closely
    ref_params, _ = load_checkpoint(tmp_path, dtype="bfloat16")
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    ref = np.asarray(logits_for_tokens(ref_params, cfg, tokens))
    got = np.asarray(logits_for_tokens(params, cfg, tokens))
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.15