"""Continuous-batching engine: greedy equivalence with the static-batch
engine, slot reuse beyond max_slots, and preemption recovery on a tiny
page pool."""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.inference.tpu.engine import TPUEngine
from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params

PAGE = 128


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,  # 320
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    return cfg, params


PROMPTS = [
    "def add(a, b):\n    return a + b\nassert add(",
    "x = 1",
    "for i in range(10):\n    print(i)",
    "class Foo:\n    pass\n" * 3,
    "y = [k * k for k in range(5)]",
]


def test_greedy_matches_static_engine(tiny):
    cfg, params = tiny
    static = TPUEngine(params, cfg, ByteTokenizer(), batch_size=2,
                       max_seq_len=512)
    paged = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512)
    want = static.generate(PROMPTS, max_new_tokens=12, temperature=0.0)
    got = paged.generate(PROMPTS, max_new_tokens=12, temperature=0.0)
    assert got == want
    paged.close()


def test_more_prompts_than_slots_preserves_order(tiny):
    cfg, params = tiny
    paged = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=256)
    outs = paged.generate(PROMPTS * 2, max_new_tokens=6, temperature=0.0)
    assert len(outs) == 2 * len(PROMPTS)
    # determinism + order: duplicated prompts give duplicated outputs
    assert outs[: len(PROMPTS)] == outs[len(PROMPTS):]
    paged.close()


def test_stop_string_frees_slot_early(tiny):
    cfg, params = tiny
    paged = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=256)
    fulls = paged.generate(PROMPTS, max_new_tokens=24, temperature=0.0)
    pick = next((i for i, f in enumerate(fulls) if len(f) > 2), None)
    assert pick is not None, f"random model produced no decodable text: {fulls!r}"
    full = fulls[pick]
    stop = full[1:3]          # a string the generation definitely contains
    cut = paged.generate([PROMPTS[pick]], max_new_tokens=24, stop=[stop],
                         temperature=0.0)[0]
    assert stop not in cut and full.startswith(cut)
    paged.close()


def test_tiny_pool_preempts_and_recovers(tiny):
    """Pool smaller than slots×max_len: sequences must preempt (recompute)
    yet still produce exactly the no-contention greedy outputs."""
    cfg, params = tiny
    roomy = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512)
    want = roomy.generate(PROMPTS[:3], max_new_tokens=8, temperature=0.0)
    roomy.close()
    # 4 usable pages, 2 slots × up to 4 pages each → contention guaranteed
    tight = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512, num_pages=5)
    got = tight.generate(PROMPTS[:3], max_new_tokens=8, temperature=0.0)
    assert got == want
    tight.close()


def test_shared_prefix_outputs_match_static(tiny):
    """Few-shot-style prompts (long common template + short unique tails)
    must trigger prefix sharing AND produce exactly the static engine's
    greedy outputs."""
    cfg, params = tiny
    template = ("You are given a Python program.\n"
                "[PYTHON]\ndef example(a):\n    return a + 1\n[/PYTHON]\n" * 6)
    prompts = [template + tail for tail in
               ["def f(x):", "x = 41", "print('hello')", "assert g(2) == 4"]]
    static = TPUEngine(params, cfg, ByteTokenizer(), batch_size=2,
                       max_seq_len=1024)
    want = static.generate(prompts, max_new_tokens=10, temperature=0.0)

    paged = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=1024)
    got = paged.generate(prompts, max_new_tokens=10, temperature=0.0)
    assert got == want
    # the shared template really was prefilled once, not per prompt:
    # template ≈ 56*6+32 chars -> >= 2 shared pages of 128
    total = sum(len(paged.tokenizer.encode(p)) for p in prompts)
    assert paged.stats.prefill_tokens < total
    # rider pages drained; the radix cache RETAINS the cached prefixes
    # (that persistence is the cross-call win) with no rider pins left
    assert (paged.rt.free_pages + paged.prefix_cache.cached_pages
            == paged.num_pages - 1)
    assert paged.prefix_cache.pinned_pages == 0
    paged.close()


def test_shared_prefix_with_preemption(tiny):
    """Prefix sharing + tiny pool: riders get preempted and recomputed,
    outputs still equal the uncontended run."""
    cfg, params = tiny
    template = "# shared few-shot header\n" + "# example line\n" * 20
    prompts = [template + t for t in ["a = 1", "b = 2", "c = 3"]]
    roomy = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=768)
    want = roomy.generate(prompts, max_new_tokens=8, temperature=0.0)
    roomy.close()
    tight = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=768, num_pages=8)
    got = tight.generate(prompts, max_new_tokens=8, temperature=0.0)
    assert got == want
    tight.close()


def test_long_prompt_multi_page_prefill(tiny):
    cfg, params = tiny
    paged = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=1024)
    static = TPUEngine(params, cfg, ByteTokenizer(), batch_size=1,
                       max_seq_len=1024)
    long_prompt = "def f(n):\n    total = 0\n" + "    total += n\n" * 40
    want = static.generate([long_prompt], max_new_tokens=8, temperature=0.0)
    got = paged.generate([long_prompt], max_new_tokens=8, temperature=0.0)
    assert got == want
    paged.close()


def test_preemption_resumes_generated_tokens(tiny):
    """At temperature>0 a preempted request must NOT resample its
    already-generated tokens: re-admission prefills prompt+generated and
    continues (vLLM recompute semantics).  We spy on re-admissions and
    assert every resumed token prefix survives into the final output."""
    import types

    cfg, params = tiny

    class NoEosTok(ByteTokenizer):
        """EOS outside the vocab: random sampling can never end a sequence
        early, so every request runs its full budget and must grow pages."""
        def __init__(self):
            super().__init__()
            self.eos_id = 10 ** 6

    # 4 usable pages, 2 slots; the two sequences together want 5 pages
    # (3 + 2: prompt page + 240 generated tokens each) → guaranteed
    # preemption when the larger one crosses into its 3rd page
    tight = PagedTPUEngine(params, cfg, NoEosTok(), max_slots=2,
                           page_size=PAGE, max_seq_len=512, num_pages=5,
                           seed=3)
    resumed: list[tuple[int, list[int]]] = []
    reqs_seen = {}
    orig = tight._prefill_admitted

    def spy(self, admitted, reqs):
        reqs_seen.update(reqs)
        for seq_id, _slot in admitted:
            if reqs[seq_id].generated:          # re-admission after preempt
                resumed.append((seq_id, list(reqs[seq_id].generated)))
        return orig(admitted, reqs)

    tight._prefill_admitted = types.MethodType(spy, tight)
    outs = tight.generate(PROMPTS[:2], max_new_tokens=240, temperature=0.8)
    assert len(outs) == 2
    assert resumed, "tiny pool should have preempted at least one request"
    for seq_id, prefix in resumed:
        final = reqs_seen[seq_id].generated
        assert final[: len(prefix)] == prefix, (
            "preemption discarded/resampled already-generated tokens")
    tight.close()


def test_sliding_window_engines_agree(tiny):
    """Greedy generation with sliding_window < prompt length: the paged
    engine (windowed Pallas/XLA paged attention) and the static engine
    (windowed dense attention, HF-parity-tested) must emit identical text."""
    import dataclasses

    cfg, params = tiny
    cfg_w = dataclasses.replace(cfg, sliding_window=48)
    long_prompt = "def f(n):\n    total = 0\n" + "    total += n\n" * 30
    static = TPUEngine(params, cfg_w, ByteTokenizer(), batch_size=1,
                       max_seq_len=1024)
    want = static.generate([long_prompt], max_new_tokens=12, temperature=0.0)
    paged = PagedTPUEngine(params, cfg_w, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=1024)
    got = paged.generate([long_prompt], max_new_tokens=12, temperature=0.0)
    assert got == want
    # and the window genuinely changes behaviour vs full attention
    full = TPUEngine(params, cfg, ByteTokenizer(), batch_size=1,
                     max_seq_len=1024)
    unwindowed = full.generate([long_prompt], max_new_tokens=12,
                               temperature=0.0)
    assert unwindowed != want
    paged.close()


def test_dp_paged_replicas_match_static(tiny):
    """dp=2 paged replicas over disjoint device groups: outputs must equal
    the single static engine's greedy outputs, in caller order."""
    import jax

    from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    cfg, params = tiny
    static = TPUEngine(params, cfg, ByteTokenizer(), batch_size=2,
                       max_seq_len=512)
    want = static.generate(PROMPTS, max_new_tokens=8, temperature=0.0)
    dpp = DataParallelPagedEngine(params, cfg, ByteTokenizer(), dp_size=2,
                                  tp_size=1, max_slots=2, page_size=PAGE,
                                  max_seq_len=512)
    got = dpp.generate(PROMPTS, max_new_tokens=8, temperature=0.0)
    assert got == want
    # replicas really sit on different devices
    d0 = next(iter(dpp.replicas[0].params["embed"].devices()))
    d1 = next(iter(dpp.replicas[1].params["embed"].devices()))
    assert d0 != d1
    dpp.close()


class TestScheduleIndependentSampling:
    """Sampling streams are keyed per request (fold_in(call_key, index) ⊕
    position), so temperature>0 output is a pure function of (seed, call
    number, request index) — independent of batch composition, chunk
    schedule, and dp placement."""

    def test_batch_composition_independence(self, tiny):
        cfg, params = tiny
        alone = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                               page_size=PAGE, max_seq_len=512, seed=11,
                               prefix_sharing=False)
        batched = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                                 page_size=PAGE, max_seq_len=512, seed=11,
                                 prefix_sharing=False)
        want = alone.generate([PROMPTS[0]], max_new_tokens=16,
                              temperature=0.8)[0]
        got = batched.generate(PROMPTS, max_new_tokens=16,
                               temperature=0.8)[0]
        assert got == want
        alone.close(); batched.close()

    def test_repeat_calls_resample(self, tiny):
        cfg, params = tiny
        eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                             page_size=PAGE, max_seq_len=512, seed=11)
        a = eng.generate([PROMPTS[0]], max_new_tokens=24, temperature=0.8)
        b = eng.generate([PROMPTS[0]], max_new_tokens=24, temperature=0.8)
        # consistency-task repeats need fresh samples each call
        assert a != b
        eng.close()

    def test_dp_placement_independence(self, tiny):
        import jax

        from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 (virtual) devices")
        cfg, params = tiny
        single = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                                page_size=PAGE, max_seq_len=512, seed=5,
                                prefix_sharing=False)
        want = single.generate(PROMPTS, max_new_tokens=16, temperature=0.8)
        single.close()
        dpp = DataParallelPagedEngine(params, cfg, ByteTokenizer(),
                                      dp_size=2, tp_size=1, max_slots=2,
                                      page_size=PAGE, max_seq_len=512,
                                      seed=5, prefix_sharing=False)
        got = dpp.generate(PROMPTS, max_new_tokens=16, temperature=0.8)
        dpp.close()
        assert got == want


def test_seq_kernel_engine_parity(tiny, monkeypatch):
    """The per-sequence streaming Pallas kernel, driven through the WHOLE
    paged engine (interpret mode on CPU), generates token-identically to
    the XLA-attention engine — the end-to-end guard for flipping
    REVAL_TPU_PAGED_BACKEND=pallas_seq on the chip."""
    cfg, params = tiny
    want_eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                              max_seq_len=512, num_pages=12)
    want = want_eng.generate(PROMPTS[:3], max_new_tokens=8, temperature=0.0)
    want_eng.close()
    monkeypatch.setenv("REVAL_TPU_PAGED_BACKEND", "pallas_seq")
    got_eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                             max_seq_len=512, num_pages=12)
    got = got_eng.generate(PROMPTS[:3], max_new_tokens=8, temperature=0.0)
    got_eng.close()
    assert got == want


def test_wide_slot_count_matches_narrow(tiny):
    """64-slot engine (the int8-KV bench candidate width) produces the
    same greedy outputs as a 2-slot engine, oversubscribed 80 prompts —
    guards the packed-state layout, PRNG fold-in, and native-runtime slot
    accounting at widths beyond the historical 32-slot shapes.  The float
    pool compares EXACTLY (one corrupted high slot index must fail);
    int8-at-width-64 is a separate approximate case because int8 pages
    round KV values."""
    cfg, params = tiny
    prompts = [p + str(i) for i, p in enumerate(PROMPTS * 16)]   # 80
    narrow = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                            page_size=PAGE, max_seq_len=256)
    want = narrow.generate(prompts, max_new_tokens=6, temperature=0.0)
    narrow.close()
    wide = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=64,
                          page_size=PAGE, max_seq_len=256)
    got = wide.generate(prompts, max_new_tokens=6, temperature=0.0)
    wide.close()
    assert got == want

    wide8 = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=64,
                           page_size=PAGE, max_seq_len=256, kv_dtype="int8")
    got8 = wide8.generate(prompts, max_new_tokens=6, temperature=0.0)
    wide8.close()
    agree = sum(a == b for a, b in zip(got8, want))
    assert agree >= 76, f"only {agree}/80 int8 outputs match the float engine"


class TestMemoryUtilization:
    """HBM-driven pool sizing (the reference's gpu_memory_utilization,
    reference inference.py:93)."""

    class _FakeDev:
        def __init__(self, limit):
            self._limit = limit

        def memory_stats(self):
            return {"bytes_limit": self._limit} if self._limit else {}

    def test_pool_sized_from_reported_hbm(self, tiny, monkeypatch):
        cfg, params = tiny
        import jax as _jax
        import jax.numpy as _jnp

        weight_bytes = sum(x.nbytes for x in
                           _jax.tree_util.tree_leaves(params))
        per_token = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * \
            _jnp.dtype(params["embed"].dtype).itemsize
        # pick the limit so the budget is comfortably POSITIVE (~100 MiB
        # past the workspace reserve): the proportional formula itself is
        # under test, not the floor clamp (that's the tight-budget case)
        limit = 2 * ((1 << 30) + weight_bytes + (100 << 20))
        monkeypatch.setattr(_jax, "local_devices",
                            lambda *a, **k: [self._FakeDev(limit)])
        eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                             page_size=PAGE, max_seq_len=256,
                             memory_utilization=0.5)
        budget = int(0.5 * limit) - weight_bytes - (1 << 30)
        want = budget // (PAGE * per_token)
        assert want > 3, "test must exercise the formula, not the clamp"
        # pages past what the slots can address are unreachable HBM
        # (advisor r4): the pool caps at 1 + slots * max_pages_per_seq
        want = min(want, 1 + 2 * (256 // PAGE))
        assert eng.num_pages == want
        eng.close()

    def test_memory_utilization_range_validated(self, tiny):
        cfg, params = tiny
        for bad in (0.0, -0.5, 1.5):
            with pytest.raises(ValueError, match="memory_utilization"):
                PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                               page_size=PAGE, max_seq_len=256,
                               memory_utilization=bad)

    def test_no_stats_falls_back_to_full_reservation(self, tiny, monkeypatch):
        cfg, params = tiny
        import jax as _jax
        monkeypatch.setattr(_jax, "local_devices",
                            lambda *a, **k: [self._FakeDev(None)])
        eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                             page_size=PAGE, max_seq_len=256,
                             memory_utilization=0.9)
        assert eng.num_pages == 1 + 2 * (256 // PAGE)
        eng.close()

    def test_tight_budget_still_generates(self, tiny, monkeypatch):
        """A budget that affords only the minimum pool (slots+1 pages)
        must still complete via preemption, not deadlock."""
        cfg, params = tiny
        import jax as _jax
        monkeypatch.setattr(_jax, "local_devices",
                            lambda *a, **k: [self._FakeDev(1 << 30)])
        eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                             page_size=PAGE, max_seq_len=256,
                             memory_utilization=0.9)
        assert eng.num_pages == 3                          # floor clamp
        outs = eng.generate(PROMPTS[:3], max_new_tokens=6, temperature=0.0)
        eng.close()
        assert len(outs) == 3 and all(isinstance(o, str) for o in outs)
