"""Taskgen: CFG partition, probe selection, and golden parity with the
shipped DREval task files (which the reference generator produced —
reference taskgen.py; the shipped JSONL is the oracle)."""

import ast
import json

import pytest

from reval_tpu.datasets import DREvalDataset
from reval_tpu.dynamics import CodeSpace, Sandbox
from reval_tpu.taskgen import (
    generate_humaneval_classeval,
    generate_mbpp,
    generate_mathqa,
    mask_asserts,
    parse_assert_statement,
    probes_for_function,
    select_probe_lines,
    select_state_probes,
)


def _trace(code: str, entry: str, *args):
    space = CodeSpace()
    fn = space.load_function(entry, code)
    sandbox = Sandbox(fn, timeout=10)
    _, trace = sandbox.run(*args)
    assert sandbox.status == "ok", sandbox.status
    return trace


# ---------------------------------------------------------------------------
# line selection
# ---------------------------------------------------------------------------

def test_select_lines_last_in_block():
    code = (
        "def f(x):\n"          # 1
        "    a = x + 1\n"      # 2
        "    b = a * 2\n"      # 3
        "    if b > 4:\n"      # 4
        "        c = b - 1\n"  # 5
        "        return c\n"   # 6
        "    return b\n"       # 7
    )
    # block [a, b, if] -> 3; if-body [c, return c] -> 6; after [return b] -> 7
    assert select_probe_lines(code) == {3, 6, 7}


def test_select_lines_loop_guard_isolated():
    code = (
        "def f(xs):\n"             # 1
        "    total = 0\n"          # 2
        "    for x in xs:\n"       # 3
        "        total += x\n"     # 4
        "    return total\n"       # 5
    )
    # [total=0] before guard; loop body [total+=x]; after [return]
    assert select_probe_lines(code) == {2, 4, 5}


def test_select_lines_skips_docstrings_and_constants():
    code = (
        "def f():\n"
        "    \"\"\"doc\"\"\"\n"    # 2: Expr(Constant) — excluded
        "    x = []\n"             # 3: Assign (still a wanted stmt kind)
        "    x.append(1)\n"        # 4
        "    return x\n"           # 5
    )
    assert select_probe_lines(code) == {5}


def test_loop_else_not_traversed():
    # The reference CFG builder ignores loop `else` bodies; shipped datasets
    # (e.g. MBPP idx 399) never contain probes there.
    code = (
        "def f(n):\n"
        "    c = 0\n"               # 2
        "    for i in range(n):\n"  # 3
        "        c += i\n"          # 4
        "    else:\n"
        "        c += 100\n"        # 6 — must NOT be selected
        "    return c\n"            # 7
    )
    assert 6 not in select_probe_lines(code)
    assert {2, 4, 7} <= select_probe_lines(code)


def test_dead_code_after_return_unreachable():
    code = (
        "def f():\n"
        "    return 1\n"   # 2
        "    x = 5\n"      # 3 — dead
    )
    assert select_probe_lines(code) == {2}


# ---------------------------------------------------------------------------
# variable selection
# ---------------------------------------------------------------------------

def test_variables_from_assignments_and_returns():
    code = (
        "def f(x):\n"
        "    a = x + 1\n"      # (2, a)
        "    b = 0\n"          # constant RHS — skipped
        "    b += a\n"         # (4, b) aug-assign always counts
        "    return b\n"       # (5, b) return of name
    )
    trace = _trace(code, "f", 3)
    probes = select_state_probes(code, trace)
    assert (2, "a") in probes and (4, "b") in probes and (5, "b") in probes
    assert all(p[1] != "b" or p[0] != 3 for p in probes)


def test_variables_trace_diff_on_mutation():
    code = (
        "def f(xs):\n"
        "    xs.append(7)\n"   # bare expr mutating xs -> trace diff
        "    return xs\n"
    )
    trace = _trace(code, "f", [1, 2])
    probes = select_state_probes(code, trace)
    assert (2, "xs") in probes


def test_return_constant_nearest_previous_var():
    code = (
        "def f(x):\n"
        "    y = x * 2\n"      # (2, y)
        "    if y > 2:\n"
        "        return True\n"   # (4, y) via fallback
        "    return False\n"
    )
    trace = _trace(code, "f", 3)
    probes = select_state_probes(code, trace)
    assert (4, "y") in probes


def test_bfs_order_final_return_gets_no_fallback():
    # HumanEval/0 pattern (nested loops): the after-loop `return False` is
    # visited via BFS *before* the inner loop body's blocks, so the
    # nearest-previous-var fallback finds nothing at visit time and the
    # final return yields no state probe.
    code = (
        "def f(xs):\n"
        "    for x in xs:\n"
        "        for z in xs:\n"
        "            y = x + z\n"        # 4
        "            if y > 10:\n"
        "                return True\n"  # 6
        "    return False\n"             # 7
    )
    trace = _trace(code, "f", [1, 20])
    probes = select_state_probes(code, trace)
    assert (6, "y") in probes
    assert all(lineno != 7 for lineno, _ in probes)


# ---------------------------------------------------------------------------
# assert parsing / masking
# ---------------------------------------------------------------------------

def test_parse_assert_statement():
    fn, args, expected = parse_assert_statement('assert foo(1, "a,b") == [2, 3]')
    assert fn == "foo" and args == "(1, 'a,b')" and expected == "[2, 3]"


def test_parse_assert_rejects_non_eq():
    with pytest.raises(ValueError):
        parse_assert_statement("assert foo(1) != 2")
    with pytest.raises(ValueError):
        parse_assert_statement("x = 1")


def test_mask_asserts_masks_every_recognised_assert():
    code = "assertTrue(obj.flag)\nassertEqual(obj.get(), 42)\n"
    masked = mask_asserts(code)
    # two-arg asserts mask the expected side, one-arg asserts their argument
    assert "assertEqual(obj.get(), ??)" in masked
    assert "assertTrue(??)" in masked


def test_mask_asserts_none_when_no_asserts():
    assert mask_asserts("x = compute()\n") is None


# ---------------------------------------------------------------------------
# golden parity with the shipped datasets
# ---------------------------------------------------------------------------

def test_humaneval_golden_parity():
    ds = DREvalDataset.load("humaneval")
    golden = {int(r["idx"]): r for r in ds.task_rows}
    rows, stats = generate_humaneval_classeval(ds, indices=list(range(0, 20)))
    compared = 0
    for row in rows:
        g = golden[row["idx"]]
        for mine, gold in zip(row["tasks"], g["tasks"]):
            compared += 1
            assert {t["lineno"] for t in mine["task"]} == \
                   {t["lineno"] for t in gold["task"]}, f"idx {row['idx']}"
            # var choice: every line's var must be a legitimate candidate —
            # exact parity is impossible because the reference iterates a
            # set (reference taskgen.py:547-548 documents the instability)
            assert mine["input_idx"] == gold["input_idx"]
    assert compared >= 40


def test_classeval_golden_parity():
    ds = DREvalDataset.load("classeval")
    golden = {int(r["idx"]): r for r in ds.task_rows}
    rows, stats = generate_humaneval_classeval(ds, indices=list(range(85, 100)))
    bad = {i for i, _ in stats.invalid}
    compared = 0
    for row in rows:
        if row["idx"] in bad:
            continue  # e.g. imports unavailable in this environment
        g = golden[row["idx"]]
        for mine, gold in zip(row["tasks"], g["tasks"]):
            compared += 1
            assert {t["lineno"] for t in mine["task"]} == \
                   {t["lineno"] for t in gold["task"]}, f"idx {row['idx']}"
    assert compared >= 20


def test_mbpp_probe_parity_sample():
    ds = DREvalDataset.load("mbpp")
    golden = {int(r["idx"]): r for r in ds.task_rows}
    checked = 0
    for idx in sorted(golden)[:40]:
        data = ds.by_idx.get(idx)
        if data is None:
            continue
        space = CodeSpace()
        fn = space.load_function(data["entry_point"], data["code"])
        sandbox = Sandbox(fn, timeout=10)
        for pair in golden[idx]["tasks"]:
            args = space.eval_invocation(data["inputs"][pair["input_idx"]])
            _, trace = sandbox.run(*args)
            assert sandbox.status == "ok"
            task = probes_for_function(data["code"], trace)
            assert {t["lineno"] for t in task} == \
                   {t["lineno"] for t in pair["task"]}, f"idx {idx}"
            checked += 1
    assert checked >= 80


def test_generate_mbpp_from_raw_rows():
    raw = [{
        "code": "def double(x):\n    y = x * 2\n    return y\n",
        "test_list": ["assert double(2) == 4", "assert double(5) == 10"],
        "test_setup_code": "",
    }]
    tasks, data, stats = generate_mbpp(raw, start_idx=154, skip_ids=frozenset(), fmt=False)
    assert len(tasks) == 1 and len(data) == 1
    assert data[0]["entry_point"] == "double"
    # single-arg inputs are auto-repaired to 1-tuples on the TypeError retry
    assert data[0]["inputs"] == ["(2,)", "(5,)"]
    assert tasks[0]["tasks"][0]["task"], "probes expected"
    assert tasks[0]["tasks"][0]["output_pred"].startswith("assert double(2)")


def test_generate_mathqa_from_raw_rows():
    raw = [{"task_id": 0, "code": "n0 = 5.0\nn1 = 3.0\nanswer = n0 * n1\n"}]
    tasks, data, stats = generate_mathqa(raw, fmt=False)
    assert len(tasks) == 1
    assert data[0]["entry_point"] == "main"
    assert data[0]["outputs"] == [15.0]
    item = tasks[0]
    assert item["idx"] == 655
    assert item["tasks"][0]["output_pred"] == "assert main()) == ??"
    linenos = {t["lineno"] for t in item["tasks"][0]["task"]}
    # straight-line body folds into one block whose last statement is the
    # `return answer` line of the main() wrapper
    assert 5 in linenos
