"""Weight-only int4 (group-wise, models/quant.py): arithmetic parity with
the dequantised oracle, engine equivalence, and tp sharding.

The reference reaches quantized checkpoints through vLLM's AWQ/GPTQ
support (reference inference.py:93); here int4 is the lever that fits
CodeLlama-34B (the CoT flagship, BASELINE.json configs[2]/[3]) on a
v5e-8 with page-pool headroom."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params
from reval_tpu.models.quant import (
    dequantize_grouped,
    dequantize_params,
    is_quantized,
    quantize_params,
    symmetric_int4_grouped,
)


def test_int4_roundtrip_error_bound():
    w = np.random.RandomState(0).randn(256, 64).astype(np.float32) * 0.1
    q, s = symmetric_int4_grouped(jnp.asarray(w), group_size=128)
    assert q.dtype == jnp.int4 and q.shape == w.shape
    assert s.shape == (2, 64)
    deq = np.asarray(dequantize_grouped(q, s, jnp.float32))
    # symmetric rounding: |w - deq| <= s/2 within each group
    bound = np.repeat(np.asarray(s), 128, axis=0) / 2 + 1e-7
    assert np.all(np.abs(w - deq) <= bound)


def test_int4_mm_matches_dequantised_oracle():
    from reval_tpu.models.model import _mm

    rng = np.random.RandomState(1)
    w = rng.randn(256, 96).astype(np.float32) * 0.05
    x = jnp.asarray(rng.randn(4, 256).astype(np.float32))
    q, s = symmetric_int4_grouped(jnp.asarray(w), group_size=64)
    got = _mm(x, {"w": q, "w_gscale": s}, "w")
    want = x @ dequantize_grouped(q, s, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_odd_in_dim_falls_back_to_divisor_group():
    w = jnp.asarray(np.random.RandomState(2).randn(192, 8).astype(np.float32))
    q, s = symmetric_int4_grouped(w, group_size=128)  # 192 % 128 != 0 → g=64
    assert s.shape[0] == 3


@pytest.fixture(scope="module")
def tiny_cfg():
    return ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                       hidden_size=256, intermediate_size=512,
                       num_layers=2, num_heads=8, num_kv_heads=4, head_dim=32)


def test_init_random_int4_structure(tiny_cfg):
    params = init_random_params(tiny_cfg, seed=0, dtype="int4")
    assert is_quantized(params)
    assert params["layers"]["q_w"].dtype == jnp.int4
    L, E = tiny_cfg.num_layers, tiny_cfg.hidden_size
    assert params["layers"]["q_w_gscale"].shape == (L, E // 128, E)
    assert params["embed"].dtype == jnp.bfloat16   # gathers stay bf16


def test_int4_engine_matches_dequantised_engine(tiny_cfg):
    """Greedy generation with int4 params is token-identical to the same
    engine fed the explicitly dequantised weights."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

    fp = init_random_params(tiny_cfg, seed=3, dtype="float32")
    q = quantize_params(fp, mode="int4")
    deq = dequantize_params(q)       # dequantises lm_head too, not just layers
    prompts = ["def add(a, b):", "x = 1", "for i in range(3):"]
    eng_q = PagedTPUEngine(q, tiny_cfg, ByteTokenizer(), max_slots=2,
                           page_size=128, max_seq_len=512)
    eng_d = PagedTPUEngine(deq, tiny_cfg, ByteTokenizer(), max_slots=2,
                           page_size=128, max_seq_len=512)
    try:
        got = eng_q.generate(prompts, max_new_tokens=16, temperature=0.0)
        want = eng_d.generate(prompts, max_new_tokens=16, temperature=0.0)
        # int4 matmul is exact w.r.t. the dequantised weights up to fp
        # association; greedy argmax over a 320-vocab random model is
        # stable under that noise
        assert got == want
    finally:
        eng_q.close()
        eng_d.close()


def test_int4_tp_sharded_matches_single_device(tiny_cfg):
    """tp=2 int4 engine (weights + gscales sharded per parallel/sharding
    rules) produces the single-device outputs exactly."""
    from reval_tpu.inference.tpu.engine import TPUEngine

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    fp = init_random_params(tiny_cfg, seed=4, dtype="float32")
    q = quantize_params(fp, mode="int4")
    prompts = ["def f(x):", "y = [1, 2, 3]"]
    single = TPUEngine(q, tiny_cfg, ByteTokenizer(), batch_size=2,
                       max_seq_len=512)
    want = single.generate(prompts, max_new_tokens=12, temperature=0.0)

    from reval_tpu.parallel import make_mesh

    mesh = make_mesh(tp=2)
    sharded = TPUEngine(q, tiny_cfg, ByteTokenizer(), batch_size=2,
                        max_seq_len=512, mesh=mesh)
    got = sharded.generate(prompts, max_new_tokens=12, temperature=0.0)
    assert got == want


def test_int4_moe_expert_path_matches_oracle():
    """MoE expert stacks quantize per (expert, group, out); the ragged
    path's transient dequant equals the oracle logits."""
    from reval_tpu.models import prefill
    from reval_tpu.models.model import init_kv_cache

    cfg = ModelConfig(vocab_size=128, hidden_size=128, intermediate_size=256,
                      num_layers=2, num_heads=4, num_kv_heads=4, head_dim=32,
                      num_experts=4, num_experts_per_tok=2)
    fp = init_random_params(cfg, seed=5, dtype="float32")
    q = quantize_params(fp, mode="int4")
    assert q["layers"]["moe_gate_w"].dtype == jnp.int4
    assert q["layers"]["moe_gate_w_gscale"].shape[:2] == (2, 4)
    deq = dequantize_params(q)

    tokens = jnp.asarray(np.random.RandomState(6).randint(0, 128, (2, 16)),
                         jnp.int32)
    pad = jnp.zeros(2, jnp.int32)
    lq, _ = prefill(q, tokens=tokens, pad_len=pad,
                    cache=init_kv_cache(cfg, 2, 32, jnp.float32), cfg=cfg)
    ld, _ = prefill(deq, tokens=tokens, pad_len=pad,
                    cache=init_kv_cache(cfg, 2, 32, jnp.float32), cfg=cfg)
    np.testing.assert_allclose(np.asarray(lq), np.asarray(ld),
                               rtol=2e-4, atol=2e-4)
