"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` — pytest imports conftest first, so
setting the env here is sufficient as long as no test module imports jax at
collection time ahead of us.
"""

import os
import sys

# Force, don't setdefault: the host may pin JAX_PLATFORMS to the TPU
# platform, where float32 matmuls take bf16 passes and parity tests drift.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# A site hook imports jax at interpreter start, before this conftest runs —
# the env vars above are then too late for jax's config, so set it directly
# (safe as long as no backend has been initialised yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Repo root on sys.path so `import reval_tpu` works without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Crash-dump bundles default to ./tpu_watch — tests that trip watchdogs or
# inject faults would litter the repo's scratch dir; send them to a tmp dir
# instead (tests asserting on bundles pass an explicit postmortem_dir,
# which wins over this env default).
if "REVAL_TPU_POSTMORTEM_DIR" not in os.environ:
    import tempfile

    os.environ["REVAL_TPU_POSTMORTEM_DIR"] = tempfile.mkdtemp(
        prefix="reval-test-postmortems-")
