"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` — pytest imports conftest first, so
setting the env here is sufficient as long as no test module imports jax at
collection time ahead of us.
"""

import os
import sys

# Force, don't setdefault: the host may pin JAX_PLATFORMS to the TPU
# platform, where float32 matmuls take bf16 passes and parity tests drift.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# A site hook imports jax at interpreter start, before this conftest runs —
# the env vars above are then too late for jax's config, so set it directly
# (safe as long as no backend has been initialised yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Repo root on sys.path so `import reval_tpu` works without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Runtime lock sanitizer (REVAL_TPU_LOCKCHECK=1): every threading.Lock
# created after this point records acquisition order (lock-order
# inversions) and the annotated serving/obs classes verify guarded-field
# writes happen lock-held.  Violations accumulate silently and fail the
# session at the end — a sanitizer must never change test behavior.
_LOCK_SANITIZER = None
# same falsy convention as reval_tpu.env.env_flag (default off when unset)
if os.environ.get("REVAL_TPU_LOCKCHECK", "0").lower() not in ("0", "false",
                                                              "off"):
    from reval_tpu.analysis import lockcheck as _lockcheck  # noqa: E402

    _LOCK_SANITIZER = _lockcheck.install(audit=True)


# Runtime recompile sanitizer (REVAL_TPU_JITCHECK=1): engine jit entry
# points count distinct compile variants; a variant past an entry's
# declared warmup budget is a violation, and the paged drive tick runs
# under a device->host transfer guard (jax's own + the Array.item/
# tolist/__array__ patch that still bites on the zero-copy CPU
# backend) so implicit syncs raise loudly.  Same accumulate-then-fail
# contract as lockcheck.
_JIT_SANITIZER = None
if os.environ.get("REVAL_TPU_JITCHECK", "0").lower() not in ("0", "false",
                                                             "off"):
    from reval_tpu.analysis import jitcheck as _jitcheck  # noqa: E402

    _JIT_SANITIZER = _jitcheck.install()


# Runtime sharding sanitizer (REVAL_TPU_SHARDCHECK=1): engines with a
# mesh guard their jit entries with declared-vs-actual sharding checks
# (ShardGuard); with the sanitizer installed every divergence is a
# violation naming the declared spec and the actual sharding.  Same
# accumulate-then-fail contract as lockcheck/jitcheck; the
# reval_shard_* counters stay on regardless.
_SHARD_SANITIZER = None
if os.environ.get("REVAL_TPU_SHARDCHECK", "0").lower() not in ("0", "false",
                                                               "off"):
    from reval_tpu.analysis import shardcheck as _shardcheck  # noqa: E402

    _SHARD_SANITIZER = _shardcheck.install()


def pytest_sessionfinish(session, exitstatus):
    for label, san in (("lockcheck", _LOCK_SANITIZER),
                       ("jitcheck", _JIT_SANITIZER),
                       ("shardcheck", _SHARD_SANITIZER)):
        if san is None or not san.violations:
            continue
        import sys as _sys

        print(f"\n{label}: runtime sanitizer violations:", file=_sys.stderr)
        for v in san.violations:
            print(f"  - [{v['kind']}] {v['detail']}", file=_sys.stderr)
        session.exitstatus = 1

# Crash-dump bundles default to ./tpu_watch — tests that trip watchdogs or
# inject faults would litter the repo's scratch dir; send them to a tmp dir
# instead (tests asserting on bundles pass an explicit postmortem_dir,
# which wins over this env default).
if "REVAL_TPU_POSTMORTEM_DIR" not in os.environ:
    import tempfile

    os.environ["REVAL_TPU_POSTMORTEM_DIR"] = tempfile.mkdtemp(
        prefix="reval-test-postmortems-")

# Kernel-CI leaderboard artifacts likewise default to ./tpu_watch — a
# stray tiny drill must not pollute the repo's artifact history (tests
# asserting on leaderboards pass an explicit --out-dir, which wins).
if "REVAL_TPU_KERNELBENCH_DIR" not in os.environ:
    import tempfile

    os.environ["REVAL_TPU_KERNELBENCH_DIR"] = tempfile.mkdtemp(
        prefix="reval-test-kernelbench-")
