"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` — pytest imports conftest first, so
setting the env here is sufficient as long as no test module imports jax at
collection time ahead of us.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Repo root on sys.path so `import reval_tpu` works without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
