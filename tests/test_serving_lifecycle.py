"""Serving-layer lifecycle hardening (fast tier — no jit, no TPU).

Everything here runs against :class:`MockStepEngine` through the REAL
session/server stack, so deadlines, admission control, the watchdog,
readiness, and graceful drain are exercised end-to-end over actual HTTP
in milliseconds: per-request deadlines cancel engine-side; overload sheds
with 429 + Retry-After and the client's RetryPolicy honors it; a stalled
engine step trips the watchdog, flips /readyz, and fails every pending
submission with a typed error; SIGTERM-style shutdown drains in-flight
work before the listener closes; and a fleet run against a
wedged-then-restarted server loses zero prompts under --resume.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from reval_tpu.inference.client import HTTPClientBackend
from reval_tpu.resilience import EngineStepChaos, RetryPolicy, wait_for_server
from reval_tpu.serving import (
    ContinuousSession,
    DeadlineExceeded,
    Draining,
    EngineServer,
    EngineWedged,
    MockStepEngine,
    MultiSession,
    Overloaded,
)

RESPONSE = "mock_model_gen"


def make_session(*, step_s=0.0, tokens_per_step=16, response=RESPONSE,
                 watchdog_s=30.0, max_queued_tokens=None, step_chaos=None):
    eng = MockStepEngine(response=response, step_s=step_s,
                         tokens_per_step=tokens_per_step)
    return eng, ContinuousSession(eng, watchdog_s=watchdog_s,
                                  max_queued_tokens=max_queued_tokens,
                                  step_chaos=step_chaos)


def make_server(session, **kw):
    kw.setdefault("max_tokens_cap", 8000)
    srv = EngineServer(session.generate_fn(), model_id="mock-serve", port=0,
                       serialize=False, **kw)
    srv.attach_session(session)
    return srv.start()


def post_raw(port, body: dict, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def get_status(port, route):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


# ---------------------------------------------------------------------------
# Baseline: the mock engine serves through the full stack
# ---------------------------------------------------------------------------

def test_mock_engine_roundtrip_over_http():
    eng, session = make_session()
    srv = make_server(session)
    try:
        client = HTTPClientBackend(model_id="m", port=srv.port, temp=0.0,
                                   prompt_type="direct", wait_for_server_s=15)
        assert client.infer_many(["a", "b", "c"]) == [RESPONSE] * 3
        assert eng.live == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Per-request deadlines
# ---------------------------------------------------------------------------

def test_deadline_expiry_mid_decode_cancels_engine_side():
    eng, session = make_session(step_s=0.02, tokens_per_step=1,
                                response="z" * 500)
    try:
        h = session.submit(["p"], max_new_tokens=400, deadline_s=0.1)
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=10)
        assert eng.stats.deadline_expired == 1
        assert eng.live == 0          # sequence released, slot freed
        # the session keeps serving after the cancel
        ok = session.submit(["q"], max_new_tokens=4)
        assert ok.result(timeout=10) == ["zzzz"]
    finally:
        session.close()


def test_deadline_maps_to_http_504_with_stable_code():
    eng, session = make_session(step_s=0.02, tokens_per_step=1,
                                response="z" * 500)
    srv = make_server(session)
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            post_raw(srv.port, {"prompt": "p", "max_tokens": 400,
                                "deadline_s": 0.1})
        assert err.value.code == 504
        body = json.loads(err.value.read())
        assert body["error"]["code"] == "deadline_exceeded"
        assert "request_id" in body["error"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Admission control / load shedding
# ---------------------------------------------------------------------------

def test_overload_sheds_429_with_retry_after():
    eng, session = make_session(step_s=0.02, tokens_per_step=1,
                                response="w" * 60, max_queued_tokens=8)
    srv = make_server(session)
    try:
        slow = session.submit(["occupies the queue"], max_new_tokens=50)
        with pytest.raises(urllib.error.HTTPError) as err:
            post_raw(srv.port, {"prompt": "shed me", "max_tokens": 4})
        assert err.value.code == 429
        assert float(err.value.headers["Retry-After"]) >= 1
        assert json.loads(err.value.read())["error"]["code"] == "overloaded"
        assert eng.stats.sheds == 1
        slow.result(timeout=60)
    finally:
        srv.shutdown()


def test_client_backs_off_and_retries_through_shed():
    """429 + Retry-After → the RetryPolicy waits and the retry lands once
    the queue drains (the acceptance loop: shed → back off → served)."""
    eng, session = make_session(step_s=0.01, tokens_per_step=1,
                                response="w" * 40, max_queued_tokens=8)
    srv = make_server(session)
    try:
        client = HTTPClientBackend(
            model_id="m", port=srv.port, temp=0.0, prompt_type="direct",
            wait_for_server_s=15,
            retry={"max_attempts": 20, "base_delay": 0.02, "max_delay": 0.1,
                   "jitter": 0.0})
        slow = session.submit(["occupies the queue"], max_new_tokens=41)
        out = client.infer_one("retry me")   # shed at least once, then served
        assert out == "w" * 40 or out.startswith("w")
        assert eng.stats.sheds >= 1
        slow.result(timeout=60)
    finally:
        srv.shutdown()


def test_lone_submission_larger_than_watermark_still_admits():
    eng, session = make_session(max_queued_tokens=4)
    try:
        h = session.submit(["a prompt far longer than four tokens"],
                           max_new_tokens=8)
        assert h.result(timeout=10)[0].startswith("mock")
        assert eng.stats.sheds == 0
    finally:
        session.close()


def test_retry_policy_honors_retry_after_header():
    sleeps = []
    policy = RetryPolicy(max_attempts=2, base_delay=50.0, jitter=0.0,
                         sleep=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise urllib.error.HTTPError(
                "http://x", 429, "overloaded",
                {"Retry-After": "2"}, None)
        return "ok"

    assert policy.call(flaky) == "ok"
    assert sleeps == [2.0]          # the hint, not base_delay=50


# ---------------------------------------------------------------------------
# Watchdog (engine-step chaos: stalled step)
# ---------------------------------------------------------------------------

def test_watchdog_trips_on_stalled_step_and_fails_pending_typed():
    chaos = EngineStepChaos(rate=1.0, modes=("stall",), stall_s=1.0,
                            max_faults=1)
    eng, session = make_session(tokens_per_step=1, watchdog_s=0.15,
                                step_chaos=chaos)
    srv = make_server(session)
    try:
        t0 = time.monotonic()
        h = session.submit(["x"], max_new_tokens=32)
        with pytest.raises(EngineWedged):
            h.result(timeout=10)          # typed failure, no hang
        assert time.monotonic() - t0 < 1.0   # well inside the stall
        assert eng.stats.watchdog_trips == 1
        # readiness flipped: /readyz 503, /healthz still pure liveness 200
        code, body = get_status(srv.port, "/readyz")
        assert code == 503 and body["wedged"] is True
        code, body = get_status(srv.port, "/healthz")
        assert code == 200 and body["status"] == "ok"
        # new submissions fail fast with the typed error (503 on the wire)
        with pytest.raises(EngineWedged):
            session.submit(["y"])
        with pytest.raises(urllib.error.HTTPError) as err:
            post_raw(srv.port, {"prompt": "y"})
        assert err.value.code == 503
        assert json.loads(err.value.read())["error"]["code"] == "engine_wedged"
    finally:
        srv.shutdown()
    assert eng.live == 0              # driver released everything on resume


def test_engine_step_exception_fails_batch_and_recovers():
    """A mid-batch engine fault errors the in-flight submissions (clients
    see a retryable 500) and the driver keeps serving — never a dead loop."""
    chaos = EngineStepChaos(rate=1.0, modes=("error",), max_faults=1)
    eng, session = make_session(step_chaos=chaos)
    try:
        h = session.submit(["x"], max_new_tokens=8)
        with pytest.raises(RuntimeError, match="chaos"):
            h.result(timeout=10)
        assert eng.live == 0
        ok = session.submit(["y"], max_new_tokens=8)
        assert ok.result(timeout=10) == [RESPONSE[:8] if len(RESPONSE) > 8
                                         else RESPONSE]
    finally:
        session.close()


def test_engine_step_chaos_schedule_is_deterministic():
    a = EngineStepChaos(rate=0.5, seed=7)
    b = EngineStepChaos(rate=0.5, seed=7)
    for chaos in (a, b):
        for _ in range(50):
            try:
                chaos.tick()
            except RuntimeError:
                pass
    assert a.injected == b.injected and a.injected


# ---------------------------------------------------------------------------
# Readiness vs liveness; MultiSession routing
# ---------------------------------------------------------------------------

def test_readyz_reflects_queue_watermark():
    eng, session = make_session(step_s=0.02, tokens_per_step=1,
                                response="w" * 60, max_queued_tokens=4)
    srv = make_server(session)
    try:
        code, _ = get_status(srv.port, "/readyz")
        assert code == 200
        slow = session.submit(["a long enough prompt"], max_new_tokens=40)
        code, body = get_status(srv.port, "/readyz")
        assert code == 503 and body["queued_tokens"] >= body["max_queued_tokens"]
        slow.result(timeout=60)
        code, _ = get_status(srv.port, "/readyz")
        assert code == 200
    finally:
        srv.shutdown()


def test_multisession_prefers_ready_replica_over_saturated():
    """A replica whose queue is over the watermark is unready; new work
    must route to the sibling WITH room, not shed from the full one."""
    eng_a = MockStepEngine(response="w" * 60, step_s=0.02, tokens_per_step=1)
    eng_b = MockStepEngine(response="w" * 60)
    ms = MultiSession([eng_a, eng_b], watchdog_s=30, max_queued_tokens=8)
    try:
        slow = ms.submit(["a prompt that fills replica a's queue"],
                         max_new_tokens=50)
        assert ms.sessions[0].readiness()["ready"] is False   # over watermark
        # tilt the load so least-loaded ALONE would pick the saturated
        # replica 0 (load 1 vs 5) — readiness routing must still send
        # these to replica 1, which has queue room
        with ms._lock:
            ms._load[1] = 5
        # sequential so replica 1's own tiny watermark never fills —
        # the point here is routing, not replica 1's shedding
        for i in range(3):
            h = ms.submit([f"p{i}"], max_new_tokens=4)
            assert h.result(timeout=10) == ["wwww"]
        assert eng_b.stats.prompts == 3
        assert eng_a.stats.sheds == 0        # never shed: routed around
        slow.result(timeout=60)
    finally:
        ms.close()


def test_multisession_routes_around_wedged_replica():
    eng_a = MockStepEngine(response=RESPONSE)
    eng_b = MockStepEngine(response=RESPONSE)
    ms = MultiSession([eng_a, eng_b], watchdog_s=30)
    try:
        ms.sessions[0].trip_watchdog()      # replica 0 is wedged
        agg = ms.readiness()
        assert agg["ready"] is True         # degraded, still serving
        assert [r["ready"] for r in agg["replicas"]] == [False, True]
        handles = [ms.submit([f"p{i}"], max_new_tokens=8) for i in range(4)]
        for h in handles:
            assert h.result(timeout=10)[0].startswith("mock")
        assert eng_a.stats.prompts == 0     # everything routed to replica 1
        assert eng_b.stats.prompts == 4
        ms.sessions[1].trip_watchdog()      # now nothing serves
        with pytest.raises(EngineWedged):
            ms.submit(["p"])
        assert ms.readiness()["ready"] is False
    finally:
        ms.close()


# ---------------------------------------------------------------------------
# Graceful drain / shutdown ordering
# ---------------------------------------------------------------------------

def test_graceful_drain_finishes_inflight_then_refuses():
    eng, session = make_session(step_s=0.02, tokens_per_step=1,
                                response="d" * 30)
    srv = make_server(session)
    results = {}

    def post():
        results["out"] = post_raw(srv.port, {"prompt": "p", "max_tokens": 31},
                                  timeout=30)

    t = threading.Thread(target=post)
    t.start()
    time.sleep(0.15)                  # request is mid-decode
    srv.shutdown()                    # drain: must NOT cut it off
    t.join(timeout=30)
    assert results["out"]["choices"][0]["text"] == "d" * 30
    assert eng.stats.drain_seconds > 0
    # listener is gone afterwards
    with pytest.raises(Exception):
        post_raw(srv.port, {"prompt": "q"}, timeout=2)
    assert eng.live == 0


def test_draining_posts_get_503_with_code():
    eng, session = make_session(step_s=0.02, tokens_per_step=1,
                                response="d" * 60)
    srv = make_server(session)
    inflight = threading.Thread(
        target=lambda: post_raw(srv.port, {"prompt": "p", "max_tokens": 40},
                                timeout=30))
    inflight.start()
    time.sleep(0.1)
    done = threading.Event()
    shutdown = threading.Thread(
        target=lambda: (srv.shutdown(), done.set()))
    shutdown.start()
    try:
        time.sleep(0.1)               # _draining flips immediately
        with pytest.raises(urllib.error.HTTPError) as err:
            post_raw(srv.port, {"prompt": "rejected"}, timeout=5)
        assert err.value.code == 503
        assert json.loads(err.value.read())["error"]["code"] == "draining"
        assert err.value.headers["Retry-After"]
    finally:
        inflight.join(timeout=30)
        shutdown.join(timeout=30)
    assert done.is_set()


def test_double_shutdown_is_idempotent():
    eng, session = make_session()
    srv = make_server(session)
    srv.shutdown()
    srv.shutdown()                    # second call: no-op, no raise
    session.close()                   # likewise idempotent at session level


def test_shutdown_closes_session_before_server_close(monkeypatch):
    eng, session = make_session()
    srv = make_server(session)
    order = []
    orig_close = session.close
    orig_server_close = srv._httpd.server_close
    monkeypatch.setattr(session, "close",
                        lambda: (order.append("session"), orig_close())[1])
    monkeypatch.setattr(srv._httpd, "server_close",
                        lambda: (order.append("socket"),
                                 orig_server_close())[1])
    srv.shutdown()
    assert order == ["session", "socket"]


def test_streaming_client_disconnect_keeps_serving():
    eng, session = make_session(step_s=0.01, tokens_per_step=1,
                                response="s" * 40)
    srv = make_server(session)
    try:
        # open an SSE request and slam the socket mid-stream
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        body = json.dumps({"prompt": "p", "stream": True,
                           "max_tokens": 41}).encode()
        sock.sendall(b"POST /v1/completions HTTP/1.1\r\n"
                     b"Host: localhost\r\nContent-Type: application/json\r\n"
                     + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        sock.recv(256)                # first bytes arrived: stream is live
        sock.close()                  # client gone
        # the engine and other requests are unaffected
        out = post_raw(srv.port, {"prompt": "q", "max_tokens": 4})
        assert out["choices"][0]["text"] == "ssss"
    finally:
        srv.shutdown()
    assert eng.live == 0


# ---------------------------------------------------------------------------
# Request validation + sanitized errors
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("body", [
    {"prompt": "p", "temperature": float("nan")},
    {"prompt": "p", "temperature": -0.5},
    {"prompt": "p", "top_p": 0.0},
    {"prompt": "p", "top_p": -1},
    {"prompt": "p", "top_k": -3},
    {"prompt": "p", "max_tokens": 0},
    {"prompt": "p", "max_tokens": "not-an-int"},
    {"prompt": "p", "deadline_s": -1},
    {"prompt": {"nested": "garbage"}},
    {"prompt": "p", "stop": [1, 2]},
])
def test_garbage_params_rejected_400(body):
    eng, session = make_session()
    srv = make_server(session)
    try:
        # NaN must survive serialisation: json allows it by default
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions", data=data,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400
        assert json.loads(err.value.read())["error"]["code"] == "invalid_request"
        # server is alive and serving afterwards
        assert post_raw(srv.port, {"prompt": "p", "max_tokens": 4})["choices"]
    finally:
        srv.shutdown()


def test_max_tokens_clamped_to_engine_budget():
    eng, session = make_session()
    srv = make_server(session)      # cap 8000
    try:
        out = post_raw(srv.port, {"prompt": "p", "max_tokens": 10**9})
        assert out["choices"][0]["text"] == RESPONSE   # served, not wedged
        assert eng.live == 0
    finally:
        srv.shutdown()


def test_negative_content_length_rejected_400():
    """Content-Length: -1 must not bypass the body cap (rfile.read(-1)
    would read until EOF — unbounded buffering on a handler thread)."""
    eng, session = make_session()
    srv = make_server(session)
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        sock.sendall(b"POST /v1/completions HTTP/1.1\r\n"
                     b"Host: localhost\r\nContent-Type: application/json\r\n"
                     b"Content-Length: -1\r\n\r\n")
        status = sock.recv(4096).decode().splitlines()[0]
        sock.close()
        assert " 400 " in status
        assert post_raw(srv.port, {"prompt": "p", "max_tokens": 4})["choices"]
    finally:
        srv.shutdown()


def test_oversized_body_rejected_413():
    eng, session = make_session()
    srv = make_server(session, max_body_bytes=1024)
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            post_raw(srv.port, {"prompt": "x" * 5000})
        assert err.value.code == 413
        assert json.loads(err.value.read())["error"]["code"] == "request_too_large"
    finally:
        srv.shutdown()


def test_500_body_never_leaks_exception_text():
    def boom(prompts, *, max_tokens, temperature, stop):
        raise RuntimeError("secret internal path /opt/x token=abc123")

    srv = EngineServer(boom, model_id="m", port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            post_raw(srv.port, {"prompt": "p"})
        assert err.value.code == 500
        raw = err.value.read().decode()
        body = json.loads(raw)
        assert body["error"]["code"] == "internal_error"
        assert body["error"]["request_id"]
        assert "secret" not in raw and "abc123" not in raw
    finally:
        srv.shutdown()


def test_wait_for_server_keeps_polling_through_503():
    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        if calls["n"] < 4:
            raise urllib.error.HTTPError("http://x/readyz", 503,
                                         "unready", {}, None)
        return {"ready": True}

    out = wait_for_server(probe, timeout=5, interval=0,
                          retry_statuses=frozenset({429, 503}),
                          sleep=lambda s: None)
    assert out == {"ready": True} and calls["n"] == 4


def test_client_handshake_waits_for_readiness_not_just_liveness():
    """A server that is up but unready (engine loading) must hold the
    handshake until /readyz flips — the old /healthz handshake would have
    connected into a 500."""
    ready = {"flag": False}
    eng, session = make_session()
    srv = EngineServer(session.generate_fn(), model_id="m", port=0,
                       serialize=False,
                       ready_fn=lambda: {"ready": ready["flag"]}).start()
    srv._session = session
    try:
        flipped = []

        def flip():
            time.sleep(0.3)
            ready["flag"] = True
            flipped.append(time.monotonic())

        threading.Thread(target=flip, daemon=True).start()
        t0 = time.monotonic()
        client = HTTPClientBackend(model_id="m", port=srv.port, temp=0.0,
                                   prompt_type="direct", wait_for_server_s=15)
        assert time.monotonic() - t0 >= 0.25      # actually waited
        assert flipped and client.infer_one("p") == RESPONSE
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Fleet vs a wedged / draining / restarted server (the acceptance loop)
# ---------------------------------------------------------------------------

def test_fleet_resume_across_wedged_then_restarted_server(tmp_path, capsys):
    """The acceptance scenario end to end: a stalled engine step wedges
    server A (watchdog trips, pending submissions fail typed — the fleet
    run aborts loudly rather than hanging or silently losing prompts);
    server A drains cleanly anyway; a healthy server B takes the same
    port; `fleet --resume` completes with ZERO lost prompts."""
    from reval_tpu.cli import main

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    cfg = {"backend": "server", "port": port, "model_id": "m",
           "dataset": "humaneval", "prompt_type": "direct",
           "repeats": 1, "max_items": 1, "progress": False,
           "results_dir": str(tmp_path / "results"),
           "wait_for_server_s": 15, "request_timeout": 30,
           "retry": {"max_attempts": 2, "base_delay": 0.01,
                     "max_delay": 0.05, "jitter": 0.0}}
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    argv = ["fleet", "-i", str(cfg_path), "--resume"]

    # server A wedges on its first engine step
    chaos = EngineStepChaos(rate=1.0, modes=("stall",), stall_s=1.5,
                            max_faults=1)
    eng_a = MockStepEngine()
    session_a = ContinuousSession(eng_a, watchdog_s=0.15, step_chaos=chaos)
    srv_a = EngineServer(session_a.generate_fn(), model_id="mock-serve",
                         port=port, serialize=False, max_tokens_cap=8000,
                         drain_timeout_s=10)
    srv_a.attach_session(session_a)
    srv_a.start()
    try:
        with pytest.raises(RuntimeError):
            main(list(argv))          # systemic failure: abort, don't hang
        assert eng_a.stats.watchdog_trips == 1
    finally:
        srv_a.shutdown()              # graceful drain works even wedged
    capsys.readouterr()

    # healthy server B on the same port; --resume finishes the run
    eng_b = MockStepEngine()
    session_b = ContinuousSession(eng_b, watchdog_s=30)
    srv_b = EngineServer(session_b.generate_fn(), model_id="mock-serve",
                         port=port, serialize=False, max_tokens_cap=8000,
                         drain_timeout_s=10)
    srv_b.attach_session(session_b)
    srv_b.start()
    try:
        assert main(list(argv)) == 0
    finally:
        srv_b.shutdown()
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["lost_prompts"] == 0
    assert summary["consistency"] is not None
    journal = tmp_path / "results" / "fleet_checkpoint.jsonl"
    assert journal.exists()
    assert len(journal.read_text().splitlines()) == 4   # 1 repeat × 4 tasks


def test_serve_mock_chaos_smoke_cli(tmp_path, capsys):
    """Tier-1 serve-path chaos smoke, mirroring `fleet --mock --chaos`:
    `serve --mock --smoke` drives concurrent prompts through the resilient
    client with engine-step chaos enabled while hammering /debugz (every
    response must parse), drains, reports counters, and — when the chaos
    schedule injected `error` faults — asserts a postmortem bundle was
    produced and parses (the smoke exits 1 otherwise)."""
    from reval_tpu.cli import main

    pm_dir = tmp_path / "postmortems"
    # seed 6 @ rate 0.5 deterministically injects `error` faults within
    # the first few steps (the schedule is keyed on step ordinal alone)
    rc = main(["serve", "--mock", "--port", "0", "--smoke", "6",
               "--chaos-step", "0.5", "--chaos-seed", "6",
               "--postmortem-dir", str(pm_dir)])
    out = capsys.readouterr().out
    assert rc == 0, out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["served"] == 6 and summary["errors"] == 0
    assert summary["debugz_scrapes"] > 0
    assert summary["chaos_injected"] > 0
    # error faults fired, so the smoke's own gate required ≥1 bundle
    assert summary["postmortems"] >= 1
    bundles = list(pm_dir.glob("postmortem-*.json"))
    assert summary["postmortems"] == len(bundles)
    assert all(json.loads(p.read_text())["reason"] == "driver_exception"
               for p in bundles)
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["served"] == 6 and summary["errors"] == 0
    for key in ("sheds", "deadline_expired", "watchdog_trips",
                "drain_seconds"):
        assert key in summary


def test_serving_counters_surface_in_fleet_trailer(tmp_path):
    """An engine whose stats saw lifecycle events gets a `serving` block
    in the fleet result (the EngineStats → fleet trailer contract)."""
    from reval_tpu.fleet import FleetRunner
    from reval_tpu.inference.mock import MockBackend

    class EngineBackend(MockBackend):
        def __init__(self):
            super().__init__(prompt_type="direct")
            self.engine = MockStepEngine()
            self.engine.stats.sheds = 3
            self.engine.stats.deadline_expired = 2
            self.engine.stats.watchdog_trips = 1
            self.engine.stats.drain_seconds = 0.25

    runner = FleetRunner(dataset="humaneval", repeats=1, max_items=1,
                         backend=EngineBackend(), progress=False,
                         resilience=False, run_consistency=False,
                         tasks=("coverage",),
                         results_dir=str(tmp_path))
    result = runner.run()
    assert result["serving"] == {"sheds": 3, "deadline_expired": 2,
                                 "watchdog_trips": 1, "drain_seconds": 0.25}


def test_engine_stats_has_lifecycle_counters():
    from reval_tpu.inference.tpu.engine import EngineStats

    s = EngineStats()
    assert (s.sheds, s.deadline_expired, s.watchdog_trips,
            s.drain_seconds) == (0, 0, 0, 0.0)


def test_draining_submit_raises_typed():
    eng, session = make_session()
    session.close()
    with pytest.raises(Draining):
        session.submit(["p"])


def test_bad_token_budget_raises_value_error_at_submit():
    eng, session = make_session()
    try:
        with pytest.raises(ValueError):
            session.submit(["p"], max_new_tokens=10**6)
        ok = session.submit(["p"], max_new_tokens=4)
        assert ok.result(timeout=10)
    finally:
        session.close()
