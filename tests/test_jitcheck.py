"""Runtime recompile sanitizer + compile-variant tracker (ISSUE 9).

Three layers under test:

1. :class:`TrackedJit` — distinct-signature counting, warmup budgets,
   the ``reval_jit_*`` counters, and the lazy-registry contract (bench
   swaps ``EngineStats`` mid-run);
2. the sanitizer — post-warmup recompiles and in-tick device→host
   transfers become violations, the drive guard trips on an injected
   ``.item()`` and stands down outside a guarded tick;
3. the real paged engine on the tiny config runs CLEAN under the
   sanitizer (zero post-warmup recompiles, zero unplanned transfers) —
   the compile-count baseline PERF.md PR-9 pins.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from reval_tpu.analysis import jitcheck
from reval_tpu.analysis.jitcheck import tracked_jit
from reval_tpu.obs.metrics import (JIT_CACHE_MISSES, JIT_COMPILES,
                                   MetricsRegistry)


@pytest.fixture
def sanitizer():
    """A FRESH scoped sanitizer, with whatever was installed before
    (e.g. the conftest session ledger under ``REVAL_TPU_JITCHECK=1``)
    restored afterwards — a test's deliberately-seeded violations must
    never land in the session ledger, and the teardown must never
    uninstall the session sanitizer."""
    with jitcheck.scoped() as san:
        yield san


# ---------------------------------------------------------------------------
# TrackedJit: variant counting + metrics
# ---------------------------------------------------------------------------

def test_tracker_counts_distinct_shape_signatures():
    reg = MetricsRegistry()
    t = tracked_jit("t.f", lambda x: x, registry=reg, warmup=8)
    t(jnp.zeros((2, 4)))
    t(jnp.zeros((2, 4)))          # same shape/dtype: no new variant
    t(jnp.zeros((4, 4)))          # new shape
    t(jnp.zeros((4, 4), jnp.int32))   # same shape, new dtype
    assert t.variants == 3 and t.misses == 0
    assert reg.counter(JIT_COMPILES).value == 3
    assert reg.counter(JIT_CACHE_MISSES).value == 0


def test_tracker_statics_and_structure_are_variant_axes():
    t = tracked_jit("t.g", lambda x, **kw: x, warmup=8)
    t(jnp.zeros((2,)), steps=4)
    t(jnp.zeros((2,)), steps=8)       # hashable static changed
    t(jnp.zeros((2,)), steps=8, mask=None)   # treedef changed
    assert t.variants == 3


def test_tracker_delegates_wrapped_attributes():
    def fn(x):
        return x

    fn.lower = lambda *a: "lowered"
    t = tracked_jit("t.d", fn)
    assert t.lower() == "lowered"
    assert t.name == "t.d"


def test_tracker_registry_may_be_lazy_callable():
    # bench swaps eng.stats (and with it the registry) between warmup
    # and the timed pass — a captured registry would go stale
    box = {"reg": MetricsRegistry()}
    t = tracked_jit("t.lazy", lambda x: x, registry=lambda: box["reg"])
    t(jnp.zeros((2,)))
    assert box["reg"].counter(JIT_COMPILES).value == 1
    box["reg"] = MetricsRegistry()     # the swap
    t(jnp.zeros((4,)))
    assert box["reg"].counter(JIT_COMPILES).value == 1
    assert t.variants == 2             # tracker-side counts are reset-proof


def test_tracker_thread_safe_variant_counting():
    t = tracked_jit("t.mt", lambda x: x, warmup=64)

    def hammer(i):
        for n in range(1, 9):
            t(jnp.zeros((n,)))

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.variants == 8             # 8 shapes, counted exactly once each


# ---------------------------------------------------------------------------
# sanitizer: post-warmup recompiles
# ---------------------------------------------------------------------------

def test_post_warmup_recompile_is_a_violation():
    san = jitcheck.JitSanitizer()
    reg = MetricsRegistry()
    t = tracked_jit("t.hot", lambda x: x, registry=reg, warmup=1,
                    sanitizer=san)
    t(jnp.zeros((2,)))                 # within budget
    assert not san.violations
    t(jnp.zeros((4,)))                 # variant #2 past warmup=1
    assert t.misses == 1
    assert reg.counter(JIT_CACHE_MISSES).value == 1
    (v,) = san.violations
    assert v["kind"] == "post-warmup-recompile"
    assert v["entry"] == "t.hot" and "warmup budget of 1" in v["detail"]


def test_shape_bucket_churn_detected_unbucketed_vs_bucketed():
    san = jitcheck.JitSanitizer()
    churn = tracked_jit("t.churn", lambda x: x, warmup=2, sanitizer=san)
    for n in range(1, 7):
        churn(jnp.zeros((n,)))         # every raw length is a new program
    assert churn.variants == 6 and churn.misses == 4
    assert sum(1 for v in san.violations
               if v["entry"] == "t.churn") == 4

    bucketed = tracked_jit("t.bucketed", lambda x: x, warmup=4,
                           sanitizer=san)
    for n in range(1, 9):
        b = 1 << (n - 1).bit_length()  # pow2 bucket, the engine contract
        bucketed(jnp.zeros((max(1, b),)))
    assert bucketed.variants == 4 and bucketed.misses == 0
    assert not any(v["entry"] == "t.bucketed" for v in san.violations)


def test_installed_sanitizer_is_the_default_sink(sanitizer):
    t = tracked_jit("t.global", lambda x: x, warmup=0)
    t(jnp.zeros((1,)))                 # warmup=0: first variant is a miss
    assert any(v["entry"] == "t.global" for v in sanitizer.violations)


def test_no_sanitizer_no_violation_still_counts():
    with jitcheck.scoped(active=False):
        assert jitcheck.current() is None
        reg = MetricsRegistry()
        t = tracked_jit("t.prod", lambda x: x, registry=reg, warmup=0)
        t(jnp.zeros((1,)))             # production mode: counted, not fatal
        assert t.misses == 1
        assert reg.counter(JIT_CACHE_MISSES).value == 1


# ---------------------------------------------------------------------------
# sanitizer: the drive guard (device→host discipline)
# ---------------------------------------------------------------------------

def test_drive_guard_trips_on_injected_item(sanitizer):
    x = jnp.arange(4)
    x.block_until_ready()
    with pytest.raises(RuntimeError, match="device->host"):
        with jitcheck.drive_guard():
            x.sum().item()             # the injected implicit sync
    assert any(v["kind"] == "implicit-device-host-transfer"
               for v in sanitizer.violations)


def test_drive_guard_trips_on_tolist(sanitizer):
    # (np.asarray reads CPU jax arrays zero-copy through the buffer
    # protocol, never calling __array__ — on this backend only the real
    # TPU transfer guard sees it; .item()/.tolist() are the patchable
    # CPU bite surface)
    x = jnp.arange(4)
    with pytest.raises(RuntimeError, match="tolist"):
        with jitcheck.drive_guard():
            x.tolist()


def test_deliberate_fetch_is_the_escape_hatch(sanitizer):
    x = jnp.arange(4)
    with jitcheck.drive_guard():
        with jitcheck.deliberate_fetch():
            got = np.asarray(x)        # the engine's one intended fetch
    assert got.tolist() == [0, 1, 2, 3]
    assert not any(v["kind"] == "implicit-device-host-transfer"
                   for v in sanitizer.violations)


def test_guard_inert_outside_drive_ticks(sanitizer):
    # tests and cold paths fetch freely even while the patch is live
    x = jnp.arange(3)
    assert np.asarray(x).sum() == 3
    assert x.tolist() == [0, 1, 2]
    assert x.sum().item() == 3


def test_guard_free_when_sanitizer_off():
    from contextlib import nullcontext

    with jitcheck.scoped(active=False):
        assert jitcheck.current() is None
        assert isinstance(jitcheck.drive_guard(), nullcontext)
        assert isinstance(jitcheck.deliberate_fetch(), nullcontext)
        with jitcheck.drive_guard():
            assert jnp.arange(2).tolist() == [0, 1]


def test_uninstall_restores_the_patched_surface():
    with jitcheck.scoped(active=False):   # park any session sanitizer
        jitcheck.install()
        jitcheck.uninstall()
        x = jnp.arange(2)
        # patched methods restored: no wrapper frames left behind
        assert type(x).tolist is not None
        assert "_d2h_wrapper" not in type(x).tolist.__qualname__
        assert np.asarray(x).tolist() == [0, 1]


def test_scoped_restores_prior_sanitizer():
    with jitcheck.scoped() as outer:      # stands in for the session install
        with jitcheck.scoped() as inner:
            t = tracked_jit("t.scoped", lambda x: x, warmup=0)
            t(jnp.zeros((1,)))
            assert any(v["entry"] == "t.scoped" for v in inner.violations)
        # the seeded violation stayed in the inner ledger, and the outer
        # sanitizer (with its d2h patch) is back in force
        assert jitcheck.current() is outer
        assert not outer.violations
        with pytest.raises(RuntimeError, match="tolist"):
            with jitcheck.drive_guard():
                jnp.arange(2).tolist()
        outer.violations.clear()          # the trip above was deliberate


# ---------------------------------------------------------------------------
# the real paged engine, tiny config, under the sanitizer
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_paged_engine_tiny_config_runs_clean(sanitizer):
    """The acceptance gate: the paged drive loop on the tiny config has
    ZERO post-warmup recompiles and ZERO unplanned device→host syncs —
    every tick ran under the guard (drive_guard is wired inside
    _drive_tick, not the test), and the one fetch is declared."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params

    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                         page_size=128, max_seq_len=512)
    prompts = ["x = 1", "def f(a):\n    return a",
               "for i in range(3):\n    print(i)"]
    outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    assert len(outs) == len(prompts)
    assert sanitizer.violations == []
    row = eng.jit_counters()
    assert row["cache_misses"] == 0
    assert row["compiles"] > 0
    # every tracked entry stayed inside its declared warmup budget
    assert set(row["entries"]) == {"paged.prefill", "paged.prefill_pctx",
                                   "paged.commit", "paged.decode_chunk",
                                   "paged.patch_tables"}
    eng.close()
