"""The north-star config's production path (round-3 verdict item 3):
CodeLlama-34B dims, tp=8, weight-only int4, paged decode.

The committed full-depth report (PERF.md "34B north star") comes from
``REVAL_TPU_DRYRUN_34B=1 python __graft_entry__.py`` — ~17 GB of
weights, minutes of XLA CPU compile.  This test drives the IDENTICAL
code path at 4 of the 48 layers (same widths: 8192 hidden, 22016 ffn,
GQA-8, vocab 32000 — only the stack is trimmed) so the suite keeps the
path green, and checks the per-chip accounting it reports:

- int4 codes at real width shard tp=8 with tp-aligned groups
  (22016/8 = 2752 → group 64) and no GSPMD reshard error;
- per-chip bytes ≈ layers x (per-layer weight bytes)/8 + embed/lm_head
  + KV pool — the extrapolation that makes 48L fit 16 GB v5e chips.
"""

import os

import pytest

pytestmark = pytest.mark.slow

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_northstar_34b_path_at_reduced_depth():
    import __graft_entry__ as ge

    report = ge.dryrun_34b_northstar(8, num_layers=4, max_new=4)
    assert report["fits_v5e_16gb"]
    # CPU accounting stores int4 UNPACKED: 1 byte per nibble (XLA s4
    # packs 2/byte on TPU — report carries a packed estimate alongside)
    h, ffn, vocab = 8192, 22016, 32000
    attn = (h * h + 2 * h * h // 8 + h * h)      # q + k,v (GQA-8) + o
    ints_per_layer = attn + 3 * h * ffn          # 1 B each unpacked
    scales_per_layer = ints_per_layer // 64 * 4  # f32, group >= 64
    per_layer = ints_per_layer + scales_per_layer
    top = vocab * h * 2 + vocab * h * 1          # bf16 embed + int4 lm_head
    expected_total = 4 * per_layer + top
    measured_total = report["per_chip_gb"] * 8 * 1024**3
    # norms/lm_head scales/KV pool add a little; sharding must not
    # replicate anything big (the band excludes e.g. a replicated embed)
    assert 0.90 < measured_total / expected_total < 1.12, (
        measured_total, expected_total)
    assert report["per_chip_packed_est_gb"] < report["per_chip_gb"]


@pytest.mark.skipif(not os.environ.get("REVAL_TPU_DRYRUN_34B"),
                    reason="full 48-layer run: ~17 GB + minutes of compile; "
                           "set REVAL_TPU_DRYRUN_34B=1 to run")
def test_northstar_34b_full_depth():
    import __graft_entry__ as ge

    report = ge.dryrun_34b_northstar(8)
    assert report["fits_v5e_16gb"]
