"""The north-star config's production path (round-3 verdict item 3):
CodeLlama-34B dims, tp=8, weight-only int4, paged decode.

The committed full-depth report (PERF.md "34B north star") comes from
``REVAL_TPU_DRYRUN_34B=1 python __graft_entry__.py`` — ~17 GB of
weights, minutes of XLA CPU compile.  This test drives the IDENTICAL
code path at 4 of the 48 layers (same widths: 8192 hidden, 22016 ffn,
GQA-8, vocab 32000 — only the stack is trimmed) so the suite keeps the
path green, and checks the per-chip accounting it reports:

- int4 codes at real width shard tp=8 with tp-aligned groups
  (22016/8 = 2752 → group 64) and no GSPMD reshard error;
- per-chip bytes ≈ layers x (per-layer weight bytes)/8 + embed/lm_head
  + KV pool — the extrapolation that makes 48L fit 16 GB v5e chips.
"""

import os

import pytest

pytestmark = pytest.mark.slow

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_northstar_34b_path_at_reduced_depth():
    import __graft_entry__ as ge

    report = ge.dryrun_34b_northstar(8, num_layers=4, max_new=4)
    assert report["fits_v5e_16gb"]
    # CPU accounting stores int4 UNPACKED: 1 byte per nibble (XLA s4
    # packs 2/byte on TPU — report carries a packed estimate alongside)
    h, ffn, vocab = 8192, 22016, 32000
    attn = (h * h + 2 * h * h // 8 + h * h)      # q + k,v (GQA-8) + o
    ints_per_layer = attn + 3 * h * ffn          # 1 B each unpacked
    scales_per_layer = ints_per_layer // 64 * 4  # f32, group >= 64
    per_layer = ints_per_layer + scales_per_layer
    top = vocab * h * 2 + vocab * h * 1          # bf16 embed + int4 lm_head
    expected_total = 4 * per_layer + top
    measured_total = report["per_chip_gb"] * 8 * 1024**3
    # norms/lm_head scales/KV pool add a little; sharding must not
    # replicate anything big (the band excludes e.g. a replicated embed)
    assert 0.90 < measured_total / expected_total < 1.12, (
        measured_total, expected_total)
    assert report["per_chip_packed_est_gb"] < report["per_chip_gb"]


@pytest.mark.skipif(not os.environ.get("REVAL_TPU_DRYRUN_34B"),
                    reason="full 48-layer run: ~17 GB + minutes of compile; "
                           "set REVAL_TPU_DRYRUN_34B=1 to run")
def test_northstar_34b_full_depth():
    import __graft_entry__ as ge

    report = ge.dryrun_34b_northstar(8)
    assert report["fits_v5e_16gb"]


def _run_70b_dryrun(num_layers: int, timeout: int) -> dict:
    """Subprocess runner: the 70B config needs a 16-device virtual mesh,
    and this test process is pinned to 8 by conftest — a fresh
    interpreter gets its own XLA device count."""
    import json
    import subprocess

    env = {**os.environ,
           "XLA_FLAGS": "--xla_force_host_platform_device_count=16",
           "JAX_PLATFORMS": "cpu"}
    code = (f"import __graft_entry__ as ge; "
            f"ge.dryrun_70b_v5p16(16, num_layers={num_layers}, max_new=2)")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=timeout,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))), env=env)
    assert r.returncode == 0, r.stderr[-3000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_configs4_70b_pp_tp_path_at_reduced_depth():
    """BASELINE configs[4] (round-4 verdict item 6): CodeLlama-70B dims,
    pp=2 x tp=8, int4, pipelined decode — 2 of the 80 layers at the real
    widths, with the full-depth per-chip extrapolation the report
    carries."""
    report = _run_70b_dryrun(num_layers=2, timeout=2400)
    assert report["fits_v5p_95gb"] and report["fits_v5p_8chip_reading"]
    # sanity-band the extrapolated full-depth bytes like the 34B test:
    # 80 layers x per-layer int4 (unpacked 1 B/nibble) / 16 devices, plus
    # embed/lm_head sharded over tp=8 (replicated across the 2 pp stages
    # only — pp_param_specs keeps param_specs' tp rules for top leaves)
    h, ffn, vocab, kvh = 8192, 28672, 32016, 8
    attn = h * h + 2 * h * (kvh * 128) + h * h
    ints_per_layer = attn + 3 * h * ffn
    per_layer = ints_per_layer + ints_per_layer // 64 * 4
    top = (vocab * h * 4 + vocab * h * 1) / 8    # f32-upcast embed + int4 head
    expected = (80 * per_layer) / 16 + top
    measured = report["per_chip_full_depth_gb"] * 1024**3
    assert 0.85 < measured / expected < 1.2, (measured, expected)


@pytest.mark.skipif(not os.environ.get("REVAL_TPU_DRYRUN_70B"),
                    reason="40-layer run at 70B widths: ~40 GB host + long "
                           "compile; set REVAL_TPU_DRYRUN_70B=1 to run")
def test_configs4_70b_half_depth():
    report = _run_70b_dryrun(num_layers=40, timeout=7200)
    assert report["fits_v5p_95gb"] and report["fits_v5p_8chip_reading"]
