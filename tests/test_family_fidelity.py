"""Full-family numerical fidelity matrix vs HF transformers at width
(round-3 verdict item 2 fallback — no network egress, no cached real
checkpoints on this host, so accuracy parity with a real pretrained model
cannot be produced; this is the compensating evidence).

The tiny per-family parity tests (test_models.py) prove implementation
correctness at toy width; test_bf16_fidelity.py proves drift behavior at
flagship width for ONE family.  A subtle RoPE / GQA / norm-offset /
softcap / MoE-routing mapping bug could still pass both and flip YES/NO
answers on a real checkpoint.  This matrix runs EVERY family surface in
models/zoo.py at meaningful width (1024 hidden × 8 layers, where bf16
reduction drift is measurable) against transformers' reference forward:

| case       | family-specific machinery it pins                         |
|------------|-----------------------------------------------------------|
| llama-gqa  | grouped KV at width (CodeLlama-34B GQA-8 geometry)         |
| mistral    | uniform sliding-window attention                           |
| gemma      | norm offset (1+w), tied embeddings, gelu, sqrt(h) embed    |
| gemma2     | logit softcap, sandwich norms, alternating local windows   |
| starcoder2 | layernorm+bias, attention bias, ungated gelu MLP           |
| mixtral    | top-2-of-N expert routing + per-expert MLPs                |

Per case: (1) fp32 cross-implementation parity per layer + logits
(tight); (2) bf16 drift within the roundoff-growth model of
test_bf16_fidelity.py; (3) greedy agreement guard.  ~0.1-0.3 B params
per case — minutes total, marked slow.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

SEQ = 96
BF16_EPS = 2.0 ** -8
OPS_PER_LAYER = 7
SAFETY = 4.0

DIMS = dict(vocab_size=2048, hidden_size=1024, num_hidden_layers=8,
            max_position_embeddings=4096)


def _llama_gqa():
    from transformers import LlamaConfig, LlamaForCausalLM

    return LlamaForCausalLM, LlamaConfig(
        **DIMS, intermediate_size=2816, num_attention_heads=8,
        num_key_value_heads=2, rope_theta=1000000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)


def _mistral():
    from transformers import MistralConfig, MistralForCausalLM

    return MistralForCausalLM, MistralConfig(
        **DIMS, intermediate_size=2816, num_attention_heads=8,
        num_key_value_heads=2, sliding_window=48, rms_norm_eps=1e-5,
        tie_word_embeddings=False)


def _gemma():
    from transformers import GemmaConfig, GemmaForCausalLM

    return GemmaForCausalLM, GemmaConfig(
        **DIMS, intermediate_size=2816, num_attention_heads=8,
        num_key_value_heads=8, head_dim=128, hidden_act="gelu_pytorch_tanh",
        rms_norm_eps=1e-6)        # gemma always ties embeddings


def _gemma2():
    from transformers import Gemma2Config, Gemma2ForCausalLM

    return Gemma2ForCausalLM, Gemma2Config(
        **DIMS, intermediate_size=2816, num_attention_heads=8,
        num_key_value_heads=4, head_dim=128,
        hidden_act="gelu_pytorch_tanh", rms_norm_eps=1e-6,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=48, query_pre_attn_scalar=128)


def _starcoder2():
    from transformers import Starcoder2Config, Starcoder2ForCausalLM

    return Starcoder2ForCausalLM, Starcoder2Config(
        **DIMS, intermediate_size=4096, num_attention_heads=8,
        num_key_value_heads=2, hidden_act="gelu_pytorch_tanh",
        norm_epsilon=1e-5, use_bias=True, tie_word_embeddings=False)


def _mixtral():
    from transformers import MixtralConfig, MixtralForCausalLM

    return MixtralForCausalLM, MixtralConfig(
        **DIMS, intermediate_size=2048, num_attention_heads=8,
        num_key_value_heads=2, num_local_experts=4, num_experts_per_tok=2,
        rms_norm_eps=1e-5, tie_word_embeddings=False)


FAMILIES = {
    "llama-gqa": _llama_gqa,
    "mistral": _mistral,
    "gemma": _gemma,
    "gemma2": _gemma2,
    "starcoder2": _starcoder2,
    "mixtral": _mixtral,
}


@pytest.mark.parametrize("family", list(FAMILIES))
def test_family_fidelity_at_width(family, tmp_path):
    import torch

    from reval_tpu.models import init_kv_cache, load_checkpoint, prefill

    cls, hf_cfg = FAMILIES[family]()
    torch.manual_seed(1234)
    model = cls(hf_cfg).eval()
    path = tmp_path / family
    model.save_pretrained(path, safe_serialization=True)

    rng = np.random.default_rng(11)
    tokens = rng.integers(0, hf_cfg.vocab_size - 1, size=(1, SEQ))
    with torch.no_grad():
        ref = model(torch.tensor(tokens), output_hidden_states=True)
    ref_hiddens = [h.float().numpy() for h in ref.hidden_states[1:]]
    ref_logits = ref.logits.float().numpy()
    del ref, model

    params, cfg = load_checkpoint(path, dtype="float32")
    pad = jnp.zeros(1, jnp.int32)
    toks = jnp.asarray(tokens, jnp.int32)

    def run(p, dtype):
        cache = init_kv_cache(cfg, 1, SEQ, dtype=dtype)
        logits, _, hiddens = prefill(p, cfg=cfg, tokens=toks, pad_len=pad,
                                     cache=cache, collect_hiddens=True)
        return (np.asarray(logits, np.float32),
                np.asarray(hiddens, np.float32))

    f32_logits, f32_hiddens = run(params, jnp.float32)

    # -- 1. fp32 cross-implementation parity, per layer + logits --------
    # (transformers norms its LAST hidden_states entry, so the final
    # pre-norm state is only observable through the logits check)
    for layer, ref_h in enumerate(ref_hiddens[:-1]):
        rel = (np.linalg.norm(f32_hiddens[layer] - ref_h)
               / np.linalg.norm(ref_h))
        assert rel < 2e-3, (
            f"[{family}] fp32 impl divergence at layer {layer}: {rel:.2e}")
    logit_rel = (np.linalg.norm(f32_logits - ref_logits)
                 / np.linalg.norm(ref_logits))
    assert logit_rel < 2e-3, f"[{family}] fp32 logits diverge: {logit_rel:.2e}"

    # -- 2. bf16 drift within the roundoff-growth model -----------------
    bf16_params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, params)
    bf16_logits, bf16_hiddens = run(bf16_params, jnp.bfloat16)
    drifts = []
    for layer in range(cfg.num_layers):
        rel = (np.linalg.norm(bf16_hiddens[layer] - f32_hiddens[layer])
               / np.linalg.norm(f32_hiddens[layer]))
        bound = SAFETY * BF16_EPS * np.sqrt(OPS_PER_LAYER * (layer + 1))
        drifts.append(rel)
        assert rel < bound, (
            f"[{family}] bf16 drift at layer {layer}: {rel:.4f} exceeds "
            f"the roundoff-growth bound {bound:.4f}")

    # -- 3. greedy effect (random weights = worst-case margins) ----------
    logit_drift = (np.linalg.norm(bf16_logits - f32_logits)
                   / np.linalg.norm(f32_logits))
    agree = float(np.mean(bf16_logits.argmax(-1) == f32_logits.argmax(-1)))
    assert logit_drift < 0.10, f"[{family}] bf16 logit drift {logit_drift:.3f}"
    assert agree > 0.5, f"[{family}] greedy agreement collapsed: {agree:.2f}"
    print(f"[{family}] drift first={drifts[0]:.4f} last={drifts[-1]:.4f} "
          f"logit-rel={logit_drift:.4f} greedy-agree={agree:.2%}")
