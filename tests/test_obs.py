"""Observability layer (fast tier — host-only, no jit, no TPU).

Covers the ISSUE-4 contracts end to end: registry semantics (counters
sum, histogram buckets add, gauges take last — the dp-replica /
MultiSession merge rule), histogram correctness at bucket boundaries,
the Prometheus exposition grammar, `/metrics` + `/statusz` over a real
mock serve stack, X-Request-Id echo on every response, retry logs naming
the request, span tracing (one nested tree per request id), the fleet
latency trailer + metrics snapshot, and the check_metrics/obs_report
tools.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import pytest

from reval_tpu.inference.tpu.engine import EngineStats
from reval_tpu.obs.metrics import (
    E2E,
    METRICS,
    QUEUE_WAIT,
    REQUESTS,
    TTFT,
    Gauge,
    Histogram,
    MetricsRegistry,
    parse_prometheus,
)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_accumulates_and_sets(self):
        reg = MetricsRegistry()
        c = reg.counter(REQUESTS)
        c.add()
        c.add(2)
        assert c.value == 3
        c.set(10)
        assert reg.counter(REQUESTS).value == 10   # same object

    def test_undeclared_name_rejected_strict(self):
        reg = MetricsRegistry()
        with pytest.raises(KeyError):
            reg.counter("reval_engine_made_up_total")
        lax = MetricsRegistry(strict=False)
        assert lax.counter("reval_engine_made_up_total").value == 0

    def test_type_collision_rejected(self):
        reg = MetricsRegistry()
        reg.counter(REQUESTS)
        with pytest.raises(ValueError):
            reg.histogram(REQUESTS)

    def test_merge_counters_sum_gauges_take_last(self):
        from reval_tpu.obs.metrics import FREE_PAGES, QUEUED_TOKENS

        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter(REQUESTS).add(3)
        b.counter(REQUESTS).add(4)
        a.gauge(QUEUED_TOKENS).set(100)
        b.gauge(QUEUED_TOKENS).set(7)
        a.gauge(FREE_PAGES).set(42)
        b.gauge(FREE_PAGES)             # registered but never SET in b
        merged = MetricsRegistry.merged([a, b])
        assert merged.counter(REQUESTS).value == 7
        assert merged.gauge(QUEUED_TOKENS).value == 7       # last set wins
        assert merged.gauge(FREE_PAGES).value == 42         # unset ≠ zero

    def test_merge_histogram_buckets_add(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        for v in (0.01, 0.3):
            a.histogram(TTFT).observe(v)
        for v in (0.01, 5.0):
            b.histogram(TTFT).observe(v)
        merged = MetricsRegistry.merged([a, b])
        h = merged.histogram(TTFT)
        assert h.count == 4
        assert h.sum == pytest.approx(5.32)
        i = h.buckets.index(0.01)
        assert h.counts[i] == 2          # both 0.01 observations in one bucket

    def test_merge_mismatched_buckets_rejected(self):
        a = MetricsRegistry(strict=False)
        b = MetricsRegistry(strict=False)
        a.histogram("reval_engine_adhoc_seconds", buckets=(1.0, 2.0))
        b.histogram("reval_engine_adhoc_seconds", buckets=(1.0, 3.0)).observe(1)
        with pytest.raises(ValueError):
            a.merge(b)


class TestHistogram:
    def test_boundary_is_inclusive(self):
        """A value exactly on a bucket bound lands IN that bucket
        (Prometheus `le` semantics), not the next one up."""
        h = Histogram("reval_request_ttft_seconds", buckets=(0.1, 0.5, 1.0))
        h.observe(0.1)
        h.observe(0.5)
        h.observe(1.0)
        assert h.counts == [1, 1, 1, 0]
        h.observe(1.0000001)             # just past the top bound → +Inf
        assert h.counts == [1, 1, 1, 1]
        h.observe(0.0)                   # bottom edge → first bucket
        assert h.counts == [2, 1, 1, 1]
        assert h.count == 5

    def test_cumulative_rendering(self):
        reg = MetricsRegistry(strict=False)
        h = reg.histogram("reval_engine_adhoc_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 2.0):
            h.observe(v)
        samples = parse_prometheus(reg.render_prometheus())
        assert samples['reval_engine_adhoc_seconds_bucket{le="0.1"}'] == 1
        assert samples['reval_engine_adhoc_seconds_bucket{le="1"}'] == 2
        assert samples['reval_engine_adhoc_seconds_bucket{le="+Inf"}'] == 3
        assert samples['reval_engine_adhoc_seconds_count'] == 3
        assert samples['reval_engine_adhoc_seconds_sum'] == pytest.approx(2.55)

    def test_percentiles_interpolate(self):
        h = Histogram("reval_request_e2e_seconds", buckets=(1.0, 2.0, 4.0))
        for _ in range(100):
            h.observe(1.5)               # all in the (1, 2] bucket
        assert 1.0 <= h.percentile(0.5) <= 2.0
        assert h.percentile(0.99) <= 2.0
        assert h.percentile(0.5) == pytest.approx(1.5, abs=0.51)

    def test_empty_percentile_zero(self):
        h = Histogram("reval_request_e2e_seconds", buckets=(1.0,))
        assert h.percentile(0.99) == 0.0


def test_tracer_caps_memory_and_reports_drops(tmp_path):
    from reval_tpu.obs.trace import Tracer

    tr = Tracer(max_events=10)
    for i in range(8):
        tr.record_request(f"r{i}", 0, t_submit=0.0, t_admit=0.1,
                          t_first=0.2, t_done=1.0, n_tokens=4)
    path = tmp_path / "t.json"
    n = tr.save(str(path))
    assert n == 10 and tr.dropped > 0
    payload = json.loads(path.read_text())
    assert payload["otherData"]["dropped_events"] == tr.dropped


def test_percentile_estimator_is_shared():
    """obs_report's percentile over the snapshot encoding must equal the
    live Histogram's — one estimator, two encodings."""
    sys.path.insert(0, TOOLS)
    try:
        import obs_report

        h = Histogram(TTFT, buckets=(0.1, 0.5, 1.0, 5.0))
        for v in (0.05, 0.2, 0.3, 0.7, 2.0, 9.0):
            h.observe(v)
        snap_h = {"buckets": [[b, c] for b, c in zip(h.buckets, h.counts)],
                  "inf": h.counts[-1], "sum": h.sum, "count": h.count}
        for q in (0.5, 0.9, 0.95, 0.99):
            assert obs_report.percentile(snap_h, q) == h.percentile(q)
    finally:
        sys.path.remove(TOOLS)


def test_exposition_grammar_rejects_garbage():
    with pytest.raises(ValueError):
        parse_prometheus("this is { not a metric line\n")
    with pytest.raises(ValueError):
        parse_prometheus("reval_requests_total not_a_number\n")


# ---------------------------------------------------------------------------
# EngineStats over the registry
# ---------------------------------------------------------------------------

class TestEngineStats:
    def test_field_compat(self):
        s = EngineStats()
        s.prompts += 2
        s.decode_seconds += 0.5
        s.prefix_hit_tokens += 128
        s.prefix_hit_tokens -= 28        # rollback path (failed insert)
        assert (s.prompts, s.prefix_hit_tokens) == (2, 100)
        assert isinstance(s.prompts, int)
        assert s.decode_seconds == pytest.approx(0.5)
        assert s.serving_counters() == {"sheds": 0, "deadline_expired": 0,
                                        "watchdog_trips": 0,
                                        "drain_seconds": 0.0}
        assert s.prefix_counters() == {"hit_tokens": 100, "hit_rate": 0.0,
                                       "evictions": 0, "inserted_pages": 0}

    def test_replica_merge_sums_counters_and_buckets(self):
        """The dp-replica / MultiSession aggregation contract: counters
        sum, histogram buckets add, gauges take last."""
        class Req:
            t_submit, t_admit, t_first, t_done = 0.0, 0.1, 0.2, 1.2
            generated = [1] * 11

        reps = [EngineStats(), EngineStats()]
        for s in reps:
            s.prompts += 3
            s.observe_request(Req())
        agg = EngineStats()
        for s in reps:
            agg.merge(s)
        assert agg.prompts == 6
        assert agg.registry.counter(REQUESTS).value == 2
        assert agg.registry.histogram(TTFT).count == 2
        assert agg.registry.histogram(E2E).sum == pytest.approx(2.4)
        lat = agg.latency_summary()
        assert lat["tpot"]["count"] == 2
        assert lat["tpot"]["mean"] == pytest.approx(0.1)
        assert set(lat) == {"queue_wait", "ttft", "tpot", "e2e"}
        for row in lat.values():
            assert row["p50"] <= row["p95"] <= row["p99"]

    def test_no_obs_disables_histograms_keeps_counters(self, monkeypatch):
        monkeypatch.setenv("REVAL_TPU_OBS", "0")
        s = EngineStats()

        class Req:
            t_submit, t_admit, t_first, t_done = 0.0, 0.1, 0.2, 1.2
            generated = [1, 2]

        s.observe_request(Req())
        s.prompts += 1
        assert s.prompts == 1
        assert s.registry.counter(REQUESTS).value == 1
        assert s.latency_summary() == {}


# ---------------------------------------------------------------------------
# serving stack: /metrics, /statusz, request ids, tracing
# ---------------------------------------------------------------------------

def _mock_server(tmp_path=None, **cfg):
    from reval_tpu.serving import serve_config

    base = {"mock": True}
    base.update(cfg)
    return serve_config(base, port=0).start()


def _post(port, body, headers=None, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


class TestServingObservability:
    def test_metrics_statusz_cover_requests(self):
        srv = _mock_server()
        try:
            n = 5
            for i in range(n):
                with _post(srv.port, {"prompt": f"p{i}", "max_tokens": 32}):
                    pass
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                samples = parse_prometheus(r.read().decode())
            assert samples["reval_requests_total"] == n
            assert samples["reval_request_ttft_seconds_count"] == n
            assert samples["reval_request_e2e_seconds_count"] == n
            assert samples["reval_request_queue_wait_seconds_count"] == n
            assert samples["reval_engine_prompts_total"] == n
            assert samples["reval_http_requests_total"] == n
            assert samples["reval_engine_step_seconds_count"] >= 1
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/statusz", timeout=10) as r:
                sz = json.load(r)
            m = sz["metrics"]
            assert m["counters"]["reval_requests_total"] == n
            assert m["histograms"]["reval_request_e2e_seconds"]["count"] == n
            assert sz["readiness"]["ready"] is True
        finally:
            srv.shutdown()

    def test_request_id_echoed_on_every_response(self):
        srv = _mock_server()
        try:
            # success echoes the caller's id
            with _post(srv.port, {"prompt": "p", "max_tokens": 8},
                       headers={"X-Request-Id": "my-id-001"}) as r:
                assert r.headers["X-Request-Id"] == "my-id-001"
            # a request without one gets a minted id back
            with _post(srv.port, {"prompt": "p", "max_tokens": 8}) as r:
                assert len(r.headers["X-Request-Id"]) >= 8
            # errors echo it too (and keep it in the body)
            try:
                with _post(srv.port, {"prompt": "p", "max_tokens": -1},
                           headers={"X-Request-Id": "bad.req-1"}):
                    raise AssertionError("expected 400")
            except urllib.error.HTTPError as exc:
                assert exc.code == 400
                assert exc.headers["X-Request-Id"] == "bad.req-1"
                assert json.load(exc)["error"]["request_id"] == "bad.req-1"
            # header injection attempts are sanitised, not relayed
            with _post(srv.port, {"prompt": "p", "max_tokens": 8},
                       headers={"X-Request-Id": "x y\tz!!"}) as r:
                assert r.headers["X-Request-Id"] == "xyz"
            # SSE responses carry it in the stream headers
            with _post(srv.port, {"prompt": "p", "max_tokens": 8,
                                  "stream": True},
                       headers={"X-Request-Id": "sse-1"}) as r:
                assert r.headers["X-Request-Id"] == "sse-1"
                r.read()
            # GETs echo when the caller sent one
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/healthz",
                headers={"X-Request-Id": "probe-7"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.headers["X-Request-Id"] == "probe-7"
        finally:
            srv.shutdown()

    def test_trace_file_has_one_span_tree_per_request(self, tmp_path):
        trace = tmp_path / "trace.json"
        srv = _mock_server(trace_out=str(trace))
        try:
            for i in range(3):
                with _post(srv.port, {"prompt": f"p{i}", "max_tokens": 16},
                           headers={"X-Request-Id": f"req-{i}"}):
                    pass
        finally:
            srv.shutdown()
        payload = json.loads(trace.read_text())
        events = payload["traceEvents"]
        roots = [e for e in events if e["name"] == "request"]
        assert len(roots) == 3
        by_rid = {e["args"]["request_id"]: e for e in roots}
        assert set(by_rid) == {"req-0", "req-1", "req-2"}
        # nesting: every child span fits inside its tid's root span
        for e in events:
            if e.get("ph") != "X" or e["name"] == "request":
                continue
            root = next(r for r in roots if r["tid"] == e["tid"])
            assert e["ts"] >= root["ts"] - 1
            assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1
        # each tree carries the queue/generate split and the ttft split
        names_per_tid = {}
        for e in events:
            if e.get("ph") == "X":
                names_per_tid.setdefault(e["tid"], set()).add(e["name"])
        for names in names_per_tid.values():
            assert {"request", "queue_wait", "generate",
                    "first_token", "decode"} <= names

    def test_smoke_cli_with_trace_and_metrics(self, tmp_path, capsys):
        from reval_tpu.cli import main

        trace = tmp_path / "t.json"
        rc = main(["serve", "--mock", "--port", "0", "--smoke", "4",
                   "--trace-out", str(trace)])
        out = capsys.readouterr().out
        assert rc == 0, out
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["served"] == 4 and summary["errors"] == 0
        assert summary["metrics_ok"] is True
        # the smoke's 4 prompts plus its one sequential receipt probe
        assert summary["requests_total"] == 5
        assert summary["ttft_count"] == 5 and summary["e2e_count"] == 5
        assert summary["receipt"] == {"receipted": True, "digest_ok": True,
                                      "fingerprints": 1}
        payload = json.loads(trace.read_text())
        assert len([e for e in payload["traceEvents"]
                    if e["name"] == "request"]) == 5


def test_multisession_metrics_merge_across_replicas():
    """Two mock replicas behind one MultiSession: /metrics-style merge
    sums both engines' counters and histogram buckets."""
    from reval_tpu.serving import EngineServer, MockStepEngine, MultiSession

    engines = [MockStepEngine(), MockStepEngine()]
    ms = MultiSession(engines)
    srv = EngineServer(ms.generate_fn(), model_id="dp-mock", port=0,
                       serialize=False, max_tokens_cap=8000)
    srv.attach_session(ms)
    srv.start()
    try:
        # saturate replica 0 so least-loaded routing spreads work
        import threading

        def post(i):
            with _post(srv.port, {"prompt": f"p{i}", "max_tokens": 8}):
                pass

        threads = [threading.Thread(target=post, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            samples = parse_prometheus(r.read().decode())
        per_engine = [e.stats.registry.histogram(E2E).count for e in engines]
        assert samples["reval_requests_total"] == 6
        assert samples["reval_request_e2e_seconds_count"] == sum(per_engine)
        assert sum(per_engine) == 6
    finally:
        srv.shutdown()


def test_retry_log_names_request():
    """Satellite: retry attempts emit structured `client.retry` events
    carrying (label=request id, attempt, budget, delay)."""
    from reval_tpu.obs.logging import recent
    from reval_tpu.resilience import RetryPolicy

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("reset")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.01, jitter=0.0,
                         sleep=lambda s: None)
    before = len([e for e in recent() if e["event"] == "client.retry"])
    assert policy.call(flaky, label="request deadbeef01") == "ok"
    events = [e for e in recent() if e["event"] == "client.retry"][before:]
    assert len(events) == 2
    assert all(e["fields"]["label"] == "request deadbeef01" for e in events)
    assert all(e["level"] == "warning" for e in events)
    first = events[0]["fields"]
    assert (first["attempt"], first["budget"]) == (1, 5)
    assert first["delay_s"] > 0
    assert "ConnectionError" in events[0]["error"]


# ---------------------------------------------------------------------------
# fleet surfacing
# ---------------------------------------------------------------------------

def test_fleet_latency_trailer_and_snapshot(tmp_path):
    """A backend exposing an instrumented engine yields a `latency` block
    (p50/p95/p99) in the fleet result and a registry snapshot file next
    to the checkpoint journal."""
    from reval_tpu.fleet import FleetRunner
    from reval_tpu.inference.mock import MockBackend
    from reval_tpu.serving import MockStepEngine

    class EngineBackend(MockBackend):
        def __init__(self):
            super().__init__(prompt_type="direct")
            self.engine = MockStepEngine()

            class Req:
                t_submit, t_admit, t_first, t_done = 0.0, 0.01, 0.05, 0.4
                generated = [1] * 8

            for _ in range(4):
                self.engine.stats.observe_request(Req())

    runner = FleetRunner(dataset="humaneval", repeats=1, max_items=1,
                         backend=EngineBackend(), progress=False,
                         resilience=False, run_consistency=False,
                         tasks=("coverage",), results_dir=str(tmp_path))
    result = runner.run()
    assert result["latency"]["ttft"]["count"] == 4
    assert result["latency"]["e2e"]["p50"] <= result["latency"]["e2e"]["p99"]
    snap_path = tmp_path / "fleet_metrics.json"
    assert snap_path.exists()
    snap = json.loads(snap_path.read_text())
    assert snap["latency"] == result["latency"]
    assert snap["metrics"]["counters"]["reval_requests_total"] == 4
    assert snap["metrics"]["histograms"]["reval_request_ttft_seconds"][
        "count"] == 4


# ---------------------------------------------------------------------------
# tools
# ---------------------------------------------------------------------------

def test_check_metrics_lint_passes():
    """The wired-in CI check: every declared metric is documented, no
    collisions, no rogue literals."""
    sys.path.insert(0, TOOLS)
    try:
        import check_metrics
        errors = check_metrics.run_checks()
    finally:
        sys.path.remove(TOOLS)
    assert errors == [], "\n".join(errors)


def test_check_metrics_catches_undocumented(tmp_path):
    """The lint actually bites: a spec metric absent from the README
    table is reported."""
    sys.path.insert(0, TOOLS)
    try:
        import check_metrics
        root = tmp_path / "repo"
        (root / "reval_tpu" / "obs").mkdir(parents=True)
        (root / "README.md").write_text("| `reval_requests_total` | c | x |\n")
        errors = check_metrics.run_checks(str(root))
    finally:
        sys.path.remove(TOOLS)
    missing = [e for e in errors if "missing from the README metric" in e]
    assert len(missing) == len(METRICS) - 1

def test_obs_report_diff_disjoint_metric_sets(tmp_path, capsys):
    """Diff mode with DISJOINT snapshots (a server restart or a metric
    added/removed between scrapes): b-only histograms diff against zero,
    a-only histograms are dropped (rendering the old totals as a
    positive 'delta' would be a lie), and a-only counters go negative —
    the visible signature of a restart."""
    sys.path.insert(0, TOOLS)
    try:
        import obs_report

        h_a = {"buckets": [[0.1, 2], [1.0, 1]], "inf": 0,
               "sum": 0.4, "count": 3}
        h_b = {"buckets": [[0.1, 5], [1.0, 0]], "inf": 1,
               "sum": 2.0, "count": 6}
        a = {"counters": {"reval_requests_total": 7},
             "gauges": {},
             "histograms": {"reval_request_ttft_seconds": h_a}}
        b = {"counters": {"reval_engine_prompts_total": 4},
             "gauges": {},
             "histograms": {"reval_request_e2e_seconds": h_b}}
        delta = obs_report.diff_snapshots(a, b)
        # a-only counter: negative delta (restart signature); b-only: full
        assert delta["counters"]["reval_requests_total"] == -7
        assert delta["counters"]["reval_engine_prompts_total"] == 4
        # a-only histogram dropped; b-only kept verbatim
        assert "reval_request_ttft_seconds" not in delta["histograms"]
        assert delta["histograms"]["reval_request_e2e_seconds"] == h_b
        # the delta still renders and its percentiles compute
        assert obs_report.percentile(h_b, 0.5) > 0
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        assert obs_report.main([str(pa), str(pb)]) == 0
        out = capsys.readouterr().out
        assert "reval_request_e2e_seconds" in out
        assert "reval_request_ttft_seconds" not in out
        assert "-7" in out
    finally:
        sys.path.remove(TOOLS)


def test_obs_report_empty_bucket_histograms(tmp_path, capsys):
    """Histograms registered but never observed (count 0, all-zero
    buckets) must not divide by zero, must stay out of the table, and an
    all-empty snapshot says so instead of printing headers over
    nothing."""
    sys.path.insert(0, TOOLS)
    try:
        import obs_report

        reg = MetricsRegistry()
        reg.histogram(TTFT)                 # registered, zero observations
        snap = reg.snapshot()
        assert snap["histograms"][TTFT]["count"] == 0
        p = tmp_path / "empty.json"
        p.write_text(json.dumps(snap))
        assert obs_report.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "empty snapshot" in out
        # diffing two empties is also clean (delta count 0 everywhere)
        assert obs_report.main([str(p), str(p)]) == 0
        out = capsys.readouterr().out
        assert "empty snapshot" in out
    finally:
        sys.path.remove(TOOLS)


def test_obs_report_gauge_only_registry(tmp_path, capsys):
    """A registry holding only gauges (e.g. a scrape before any request
    arrived) renders its gauge table; a diff keeps b's gauge LEVELS
    (a gauge is a level, not a flow — never subtracted)."""
    sys.path.insert(0, TOOLS)
    try:
        import obs_report
        from reval_tpu.obs.metrics import FREE_PAGES, QUEUED_TOKENS

        rega, regb = MetricsRegistry(), MetricsRegistry()
        rega.gauge(FREE_PAGES).set(100)
        rega.gauge(QUEUED_TOKENS).set(5)
        regb.gauge(FREE_PAGES).set(37)
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(rega.snapshot()))
        pb.write_text(json.dumps(regb.snapshot()))
        assert obs_report.main([str(pa)]) == 0
        out = capsys.readouterr().out
        assert FREE_PAGES in out and "100" in out
        assert obs_report.main([str(pa), str(pb)]) == 0
        out = capsys.readouterr().out
        line = next(l for l in out.splitlines() if l.startswith(FREE_PAGES))
        assert line.split()[-1] == "37.0"       # b's level, not 37-100
        assert QUEUED_TOKENS not in out         # absent in b: not a delta
    finally:
        sys.path.remove(TOOLS)


def test_fleet_skips_snapshot_when_no_requests_completed(tmp_path):
    """Satellite: a fully-journaled `--resume` run (zero new inference)
    must NOT clobber the previous run's fleet_metrics.json with an
    empty shell — and must not print a latency trailer."""
    from reval_tpu.fleet import FleetRunner
    from reval_tpu.inference.mock import MockBackend
    from reval_tpu.serving import MockStepEngine

    class EngineBackend(MockBackend):
        def __init__(self):
            super().__init__(prompt_type="direct")
            self.engine = MockStepEngine()

    previous = {"ts": "earlier", "metrics": {"counters":
                {"reval_requests_total": 42}}}
    snap_path = tmp_path / "fleet_metrics.json"
    snap_path.write_text(json.dumps(previous))

    runner = FleetRunner(dataset="humaneval", repeats=1, max_items=1,
                         backend=EngineBackend(), progress=False,
                         resilience=False, run_consistency=False,
                         tasks=("coverage",), results_dir=str(tmp_path))
    # simulate the fully-journaled resume: nothing retires on the engine
    result = runner.run()
    assert "latency" not in result
    assert json.loads(snap_path.read_text()) == previous   # untouched


def test_obs_report_renders_and_diffs(tmp_path, capsys):
    sys.path.insert(0, TOOLS)
    try:
        import obs_report

        reg = MetricsRegistry()
        reg.counter(REQUESTS).add(5)
        for v in (0.01, 0.02, 0.3):
            reg.histogram(QUEUE_WAIT).observe(v)
        a = tmp_path / "a.json"
        a.write_text(json.dumps(reg.snapshot()))
        reg.counter(REQUESTS).add(2)
        reg.histogram(QUEUE_WAIT).observe(1.5)
        b = tmp_path / "b.json"
        b.write_text(json.dumps({"metrics": reg.snapshot()}))  # fleet nesting
        assert obs_report.main([str(a)]) == 0
        single = capsys.readouterr().out
        assert "reval_request_queue_wait_seconds" in single
        assert obs_report.main([str(a), str(b)]) == 0
        delta = capsys.readouterr().out
        assert "reval_requests_total" in delta
        # the diff sees only the 2 new requests and the 1 new observation
        line = next(l for l in delta.splitlines()
                    if l.startswith("reval_request_queue_wait_seconds"))
        assert " 1 " in line
        line = next(l for l in delta.splitlines()
                    if l.startswith("reval_requests_total"))
        assert line.split()[-1] == "2"
    finally:
        sys.path.remove(TOOLS)
