"""Runtime sharding sanitizer (analysis/shardcheck.py).

Layers under test (ISSUE 11):

1. the ShardGuard compares declared vs actual shardings without
   touching behavior — clean calls record nothing, every comparison
   rides ``reval_shard_checks_total``;
2. the seeded spec-mismatch DRILL trips the sanitizer with the
   declared-vs-actual sharding named, bumps
   ``reval_shard_respec_total``, and emits ONE ``shard.respec`` event
   per distinct signature (no log storm at chunk cadence);
3. the ``scoped()`` ledger pattern isolates seeded violations from a
   session-level ``REVAL_TPU_SHARDCHECK=1`` install;
4. a REAL paged engine at a tiny tp-mesh config drives a full
   generate() under the sanitizer and stays clean (slow tier — the
   same config test_parallel pins numerically).
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from reval_tpu.analysis import shardcheck  # noqa: E402
from reval_tpu.analysis.shardcheck import ShardGuard  # noqa: E402
from reval_tpu.obs import logging as obs_logging  # noqa: E402
from reval_tpu.obs.metrics import (  # noqa: E402
    SHARD_CHECKS, SHARD_RESPECS, MetricsRegistry)
from reval_tpu.parallel import make_mesh  # noqa: E402


@pytest.fixture
def mesh():
    return make_mesh(tp=2)


def put(mesh, spec, shape=(4, 8)):
    return jax.device_put(jnp.zeros(shape, jnp.float32),
                          NamedSharding(mesh, spec))


def guard_for(mesh, declared_in, declared_out, reg):
    return ShardGuard(
        "test.entry", lambda *a, **k: a[0],
        in_checks={0: NamedSharding(mesh, declared_in)},
        out_checks={0: NamedSharding(mesh, declared_out)},
        registry=reg)


def test_clean_call_records_nothing(mesh):
    reg = MetricsRegistry()
    g = guard_for(mesh, P("tp"), P("tp"), reg)
    with shardcheck.scoped() as san:
        g(put(mesh, P("tp")))
    assert san.violations == []
    snap = reg.snapshot()
    assert snap["counters"][SHARD_CHECKS] == 2      # one in, one out
    assert snap["counters"].get(SHARD_RESPECS, 0) == 0


def test_mismatch_drill_names_declared_and_actual(mesh):
    """The acceptance drill: a seeded spec mismatch trips the sanitizer
    with BOTH sides of the divergence named."""
    reg = MetricsRegistry()
    g = guard_for(mesh, P(), P(), reg)              # declares replicated
    with shardcheck.scoped() as san:
        g(put(mesh, P("tp")))                       # actually tp-sharded
    assert len(san.violations) == 2                 # input + output site
    v = san.violations[0]
    assert v["kind"] == "sharding-respec"
    assert v["entry"] == "test.entry"
    assert "NamedSharding(PartitionSpec())" in v["detail"]
    assert "'tp'" in v["detail"]                    # the actual sharding
    assert reg.snapshot()["counters"][SHARD_RESPECS] == 2


def test_mismatch_dedupes_events_but_counts_every_call(mesh):
    reg = MetricsRegistry()
    g = guard_for(mesh, P(), P(), reg)
    with shardcheck.scoped() as san:
        x = put(mesh, P("tp"))
        g(x)
        g(x)
        g(x)
    # the counter slopes with every mismatched call…
    assert reg.snapshot()["counters"][SHARD_RESPECS] == 6
    # …but the ledger (and the shard.respec event) carries one entry
    # per distinct (site, actual) signature
    assert len(san.violations) == 2
    events = [e for e in obs_logging.recent(64)
              if e.get("event") == "shard.respec"
              and e.get("fields", {}).get("entry") == "test.entry"]
    assert len(events) >= 2
    assert all("declared" in e["fields"] and "actual" in e["fields"]
               for e in events)


def test_committed_single_device_value_is_a_respec(mesh):
    """A fully-committed single-device array where a sharded spec was
    declared is the classic 'forgot the device_put' divergence."""
    reg = MetricsRegistry()
    g = guard_for(mesh, P("tp"), P("tp"), reg)
    with shardcheck.scoped() as san:
        g(jnp.zeros((4, 8), jnp.float32))
    assert san.violations
    assert "SingleDeviceSharding" in san.violations[0]["detail"]


def test_pytree_checked_leafwise_lower_rank_skipped(mesh):
    reg = MetricsRegistry()
    expected = NamedSharding(mesh, P(None, "tp", None))
    g = ShardGuard("test.tree", lambda tree: tree,
                   in_checks={0: expected}, registry=reg)
    pool = jax.device_put(jnp.zeros((4, 2, 16)), expected)
    scale = jnp.zeros((4, 2))               # rank 2 < spec rank 3: skipped
    with shardcheck.scoped() as san:
        g({"pool": pool, "scale": scale})
    assert san.violations == []
    assert reg.snapshot()["counters"][SHARD_CHECKS] == 1


def test_replicated_spec_checks_any_rank(mesh):
    reg = MetricsRegistry()
    expected = NamedSharding(mesh, P())     # rank-0 spec covers any array
    g = ShardGuard("test.rep", lambda x: x, in_checks={0: expected},
                   registry=reg)
    with shardcheck.scoped() as san:
        g(put(mesh, P(), shape=(8, 8)))
        assert not san.violations
        g(put(mesh, P("tp"), shape=(8, 8)))
        assert san.violations


def test_scoped_isolates_session_install(mesh):
    # park any conftest-level REVAL_TPU_SHARDCHECK install so this
    # test's own install/uninstall cycle never mutates the session's
    with shardcheck.scoped(active=False):
        session = shardcheck.install()
        try:
            g = guard_for(mesh, P(), P(), None)
            with shardcheck.scoped() as inner:
                g(put(mesh, P("tp")))
                assert inner.violations
            # the seeded violations never reached the session ledger,
            # and the session install survived the scope
            assert shardcheck.current() is session
            assert session.violations == []
            with shardcheck.scoped(active=False):
                assert shardcheck.current() is None
            assert shardcheck.current() is session
        finally:
            shardcheck.uninstall()
        assert shardcheck.current() is None


def test_guard_off_still_counts_metrics(mesh):
    """Sanitizer off: no ledger anywhere, but the reval_shard_* counters
    keep slopes production can alert on."""
    with shardcheck.scoped(active=False):
        assert shardcheck.current() is None
        reg = MetricsRegistry()
        g = guard_for(mesh, P(), P(), reg)
        g(put(mesh, P("tp")))
    assert reg.snapshot()["counters"][SHARD_RESPECS] == 2


def test_guard_delegates_wrapped_attributes(mesh):
    from reval_tpu.analysis.jitcheck import tracked_jit

    tracked = tracked_jit("test.tracked", lambda x: x, warmup=4)
    g = ShardGuard("test.tracked", tracked, registry=None)
    g(put(mesh, P("tp")))
    assert g.variants == 1                  # TrackedJit accounting rides
    assert g.warmup == 4
    assert g.name == "test.tracked"


def test_unresolved_check_is_loud_not_inert(mesh):
    """A declared check that stops matching the call shape (refactor
    went positional, output tuple shrank) must SAY so — an inert guard
    reads exactly like a clean one otherwise."""
    reg = MetricsRegistry()
    ns = NamedSharding(mesh, P("tp"))
    g = ShardGuard("test.kw", lambda *a, **k: k.get("cache"),
                   in_checks={"cache": ns, 5: ns}, out_checks={3: ns},
                   registry=reg)
    with shardcheck.scoped() as san:
        g(cache=put(mesh, P("tp")))         # index 5 / output 3 absent
        g(cache=put(mesh, P("tp")))         # …and deduped on repeat
    # the resolvable kwarg was checked cleanly; the two unresolved
    # sites are each flagged exactly once
    assert reg.snapshot()["counters"][SHARD_CHECKS] == 2
    assert len(san.violations) == 2
    assert all("unresolved" in v["detail"] for v in san.violations)
    sites = {v["detail"].split(":")[0] for v in san.violations}
    assert any("input 5" in s for s in sites)
    assert any("output [3]" in s for s in sites)


@pytest.mark.slow
def test_real_paged_engine_tiny_config_is_shardcheck_clean():
    """One real paged-engine run over a tp=2 mesh: generate() end to
    end under the sanitizer, zero declared-vs-actual divergences, and
    the guard demonstrably LOOKED (checks counter moved)."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params

    cfg = ModelConfig(
        vocab_size=ByteTokenizer.vocab_size, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16)
    params = init_random_params(cfg, seed=0, dtype="float32")
    mesh = make_mesh(tp=2)
    with shardcheck.scoped() as san:
        eng = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=3,
                             page_size=64, max_seq_len=256, mesh=mesh,
                             prefix_sharing=False)
        texts = eng.generate(["hello world", "paged engines"],
                             max_new_tokens=8, temperature=0.0)
        eng.close()
        assert len(texts) == 2
        assert san.violations == [], san.violations
    snap = eng.stats.registry.snapshot()
    assert snap["counters"][SHARD_CHECKS] > 0
    assert snap["counters"].get(SHARD_RESPECS, 0) == 0
