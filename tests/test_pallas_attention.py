"""Paged-attention kernel: Pallas (interpret mode) vs XLA reference vs the
contiguous-cache decode attention already validated by test_models.

Cache layout under test is the token-major flat pool ``[N * P, H_kv, D]``
(models/paged.py): page ``n`` is rows ``[n * P, (n + 1) * P)``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.ops.attention import decode_attention
from reval_tpu.ops.pallas_attention import (
    paged_decode_attention_pallas,
    paged_decode_attention_pallas_seq,
    paged_decode_attention_xla,
)

# both TPU kernels must match the XLA oracle bit-for-bit in interpret mode:
# the per-(seq, page) grid kernel and the per-sequence streaming kernel,
# each under both in-kernel dot formulations (swap / wide — see
# ops.pallas_attention._page_scores)
from functools import partial

KERNELS = [paged_decode_attention_pallas, paged_decode_attention_pallas_seq,
           partial(paged_decode_attention_pallas, dot_mode="wide"),
           partial(paged_decode_attention_pallas_seq, dot_mode="wide")]
KERNEL_IDS = ["page-grid", "per-seq", "page-grid-wide", "per-seq-wide"]

PAGE = 128


def page_view(flat, n_pages):
    """[N*P, H_kv, D] → [N, P, H_kv, D] (the indexing the helpers use)."""
    return flat.reshape(n_pages, PAGE, *flat.shape[1:])


def set_page(flat, page, value):
    """Overwrite one page of a flat pool with a scalar."""
    return flat.at[page * PAGE:(page + 1) * PAGE].set(value)


def make_paged(seed=0, b=4, h=8, h_kv=4, d=128, n_pages=16, max_pages=3,
               dtype=jnp.float32):
    """Random q + paged cache with distinct per-sequence lengths/tables."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, h, d)), dtype)
    k_pages = jnp.asarray(rng.standard_normal((n_pages * PAGE, h_kv, d)), dtype)
    v_pages = jnp.asarray(rng.standard_normal((n_pages * PAGE, h_kv, d)), dtype)
    # unique page ids per (seq, slot) so a wrong table lookup changes numbers
    tables = jnp.asarray(
        rng.permutation(n_pages)[: b * max_pages].reshape(b, max_pages),
        jnp.int32)
    seq_lens = jnp.asarray(rng.integers(1, max_pages * PAGE, size=b), jnp.int32)
    return q, k_pages, v_pages, tables, seq_lens


@pytest.mark.parametrize("kernel", KERNELS, ids=KERNEL_IDS)
def test_pallas_kernel_matches_xla_reference(kernel):
    q, kp, vp, tables, lens = make_paged()
    ref = paged_decode_attention_xla(q, kp, vp, tables, lens, page_size=PAGE)
    out = kernel(q, kp, vp, tables, lens, page_size=PAGE, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kernel", KERNELS, ids=KERNEL_IDS)
def test_pallas_kernel_mha_single_group(kernel):
    q, kp, vp, tables, lens = make_paged(seed=1, h=4, h_kv=4)  # G == 1
    ref = paged_decode_attention_xla(q, kp, vp, tables, lens, page_size=PAGE)
    out = kernel(q, kp, vp, tables, lens, page_size=PAGE, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_paged_xla_matches_contiguous_decode():
    """Scatter a contiguous (unpadded) cache into pages; both attention
    implementations must agree on every sequence."""
    rng = np.random.default_rng(2)
    b, h, h_kv, d, max_pages = 2, 8, 2, 128, 2
    s = max_pages * PAGE
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h_kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h_kv, d)), jnp.float32)
    seq_lens = jnp.asarray([PAGE + 7, 3], jnp.int32)

    # contiguous path: right-aligned validity via pad_len=0, cur_pos=len-1
    outs = []
    for i in range(b):
        outs.append(decode_attention(
            q[i:i + 1], k[i:i + 1], v[i:i + 1],
            pad_len=jnp.zeros(1, jnp.int32), cur_pos=seq_lens[i] - 1))
    contiguous = jnp.concatenate(outs)[:, 0]

    # paged view of the same data: row b's page j is pool page b*max_pages+j,
    # so the flat pool is just the concatenated per-row token streams
    tables = jnp.arange(b * max_pages, dtype=jnp.int32).reshape(b, max_pages)
    k_pages = k.reshape(b * s, h_kv, d)
    v_pages = v.reshape(b * s, h_kv, d)
    paged = paged_decode_attention_xla(
        q[:, 0], k_pages, v_pages, tables, seq_lens, page_size=PAGE)
    np.testing.assert_allclose(np.asarray(paged), np.asarray(contiguous),
                               rtol=1e-5, atol=1e-5)


def test_padding_pages_never_leak():
    """Table slots past the active length point at a poisoned page; the
    output must not change."""
    q, kp, vp, tables, lens = make_paged(seed=3, max_pages=2)
    lens = jnp.minimum(lens, PAGE)          # every sequence fits in 1 page
    base = paged_decode_attention_xla(q, kp, vp, tables, lens, page_size=PAGE)
    poisoned = kp
    for page in np.asarray(tables[:, 1]):
        poisoned = set_page(poisoned, int(page), 1e9)
    out = paged_decode_attention_xla(q, poisoned, vp, tables, lens,
                                     page_size=PAGE)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=1e-6, atol=1e-6)
    for kernel in KERNELS:
        out_p = kernel(q, poisoned, vp, tables, lens,
                       page_size=PAGE, interpret=True)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(base),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("window", [1, 64, 200, 1000])
@pytest.mark.parametrize("kernel", KERNELS, ids=KERNEL_IDS)
def test_windowed_pallas_matches_xla(kernel, window):
    q, kp, vp, tables, lens = make_paged(seed=4)
    ref = paged_decode_attention_xla(q, kp, vp, tables, lens,
                                     page_size=PAGE, window=window)
    out = kernel(q, kp, vp, tables, lens, page_size=PAGE, interpret=True,
                 window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_windowed_xla_matches_contiguous_decode():
    """Windowed paged attention vs the contiguous-cache decode_attention
    (itself HF-parity-tested): gather each sequence into a dense cache and
    compare, window smaller than the live length."""
    window = 100
    q, kp, vp, tables, lens = make_paged(seed=5)
    got = paged_decode_attention_xla(q, kp, vp, tables, lens,
                                     page_size=PAGE, window=window)
    b, h, d = q.shape
    h_kv = kp.shape[1]
    n_pages = kp.shape[0] // PAGE
    s_max = tables.shape[1] * PAGE
    k_seq = page_view(kp, n_pages)[tables].reshape(b, s_max, h_kv, d)
    v_seq = page_view(vp, n_pages)[tables].reshape(b, s_max, h_kv, d)
    for row in range(b):
        cur = int(lens[row]) - 1                  # query's own position
        ref = decode_attention(
            q[row:row + 1, None], k_seq[row:row + 1], v_seq[row:row + 1],
            pad_len=jnp.zeros(1, jnp.int32), cur_pos=jnp.int32(cur),
            window=window)
        np.testing.assert_allclose(np.asarray(got[row]),
                                   np.asarray(ref[0, 0]),
                                   rtol=1e-5, atol=1e-5)


def test_window_excludes_old_keys():
    """Corrupting keys OUTSIDE the window must not change the output;
    corrupting keys INSIDE it must."""
    window = 96
    q, kp, vp, tables, lens = make_paged(seed=6, b=1, max_pages=3)
    lens = jnp.asarray([3 * PAGE - 5], jnp.int32)   # long seq, window ≪ len
    base = paged_decode_attention_xla(q, kp, vp, tables, lens,
                                      page_size=PAGE, window=window)
    kp_bad = set_page(kp, int(tables[0, 0]), 1e3)   # far outside the window
    out = paged_decode_attention_xla(q, kp_bad, vp, tables, lens,
                                     page_size=PAGE, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base))
    kp_bad = set_page(kp, int(tables[0, 2]), 1e3)   # inside the window
    out = paged_decode_attention_xla(q, kp_bad, vp, tables, lens,
                                     page_size=PAGE, window=window)
    assert not np.allclose(np.asarray(out), np.asarray(base))
