"""Shared-prefix reservations in the native runtime: refcounted attachment
at admission, survival across release ordering, and preemption re-attach."""

import pytest

from reval_tpu.runtime import PagedRuntime

PAGE = 16


@pytest.fixture
def rt():
    r = PagedRuntime(num_pages=12, page_size=PAGE, max_slots=3,
                     max_pages_per_seq=6)
    yield r
    r.close()


def test_riders_share_prefix_pages(rt):
    pre = rt.alloc_prefix(2)
    pre_pages = [p for p in rt.block_table(pre) if p != 0]
    assert len(pre_pages) == 2
    a = rt.submit_prefixed(pre, prompt_len=2 * PAGE + 5, max_new_tokens=4)
    b = rt.submit_prefixed(pre, prompt_len=2 * PAGE + 9, max_new_tokens=4)
    assert len(rt.admit()) == 2
    ta, tb = rt.block_table(a), rt.block_table(b)
    assert list(ta[:2]) == pre_pages and list(tb[:2]) == pre_pages
    assert ta[2] != tb[2] and ta[2] not in pre_pages   # own suffix pages
    assert rt.page_ref(pre_pages[0]) == 3              # prefix + 2 riders
    assert rt.seq_len(a) == 2 * PAGE + 5
    # only 2 pages allocated beyond the prefix (1 suffix page each)
    assert rt.free_pages == 11 - 2 - 2


def test_prefix_survives_until_last_rider(rt):
    pre = rt.alloc_prefix(1)
    page = rt.block_table(pre)[0]
    a = rt.submit_prefixed(pre, PAGE + 1, 0)
    rt.admit()
    rt.release(pre)                    # engine done submitting riders
    assert rt.page_ref(page) == 1      # rider keeps it alive
    rt.release(a)
    assert rt.page_ref(page) == 0      # now free


def test_preempted_rider_reattaches(rt):
    pre = rt.alloc_prefix(1)
    page = rt.block_table(pre)[0]
    a = rt.submit_prefixed(pre, PAGE + 1, PAGE)
    rt.admit()
    assert rt.page_ref(page) == 2
    victim = rt.preempt_last()
    assert victim == a
    assert rt.page_ref(page) == 1      # detached on preemption
    assert [s for s, _ in rt.admit()] == [a]
    assert rt.page_ref(page) == 2      # re-attached
    assert list(rt.block_table(a))[0] == page


def test_submit_prefixed_validations(rt):
    pre = rt.alloc_prefix(2)
    with pytest.raises(ValueError):    # prompt must extend past the prefix
        rt.submit_prefixed(pre, 2 * PAGE, 4)
    with pytest.raises(ValueError):    # unknown prefix
        rt.submit_prefixed(12345, 3 * PAGE, 4)
    rt.release(pre)
    with pytest.raises(ValueError):    # dead prefix
        rt.submit_prefixed(pre, 3 * PAGE, 4)


def test_alloc_prefix_oom(rt):
    with pytest.raises(ValueError):
        rt.alloc_prefix(100)


def test_prefix_pages_query(rt):
    pre = rt.alloc_prefix(2)
    a = rt.submit_prefixed(pre, prompt_len=2 * PAGE + 5, max_new_tokens=4)
    assert rt.prefix_pages(a) == 0          # waiting: nothing attached yet
    rt.admit()
    assert rt.prefix_pages(a) == 2
    assert rt.preempt_last() == a
    assert rt.prefix_pages(a) == 0          # detached with its pages
    rt.admit()
    assert rt.prefix_pages(a) == 2          # re-attached
    with pytest.raises(KeyError):
        rt.prefix_pages(99999)


def test_alloc_prefix_extend_shares_parent_pages(rt):
    """The radix-tree building block: a child prefix refcounts every
    parent page and owns only its fresh tail."""
    pre = rt.alloc_prefix(2)
    parent_pages = [p for p in rt.block_table(pre) if p != 0]
    child = rt.alloc_prefix_extend(pre, 1)
    child_pages = [p for p in rt.block_table(child) if p != 0]
    assert child_pages[:2] == parent_pages and len(child_pages) == 3
    assert all(rt.page_ref(p) == 2 for p in parent_pages)
    assert rt.page_ref(child_pages[2]) == 1
    assert rt.seq_len(child) == 3 * PAGE
    # riders of the child attach the WHOLE chain
    a = rt.submit_prefixed(child, 3 * PAGE + 2, 0)
    rt.admit()
    assert rt.prefix_pages(a) == 3
    assert rt.page_ref(parent_pages[0]) == 3
    # releasing the child frees only its own page (parent holds the rest)
    rt.release(a)
    rt.release(child)
    assert all(rt.page_ref(p) == 1 for p in parent_pages)
    assert rt.free_pages == 11 - 2
    rt.release(pre)
    assert rt.free_pages == 11


def test_alloc_prefix_extend_validations(rt):
    pre = rt.alloc_prefix(1)
    with pytest.raises(ValueError):      # unknown parent
        rt.alloc_prefix_extend(12345, 1)
    with pytest.raises(ValueError):      # n_pages < 1
        rt.alloc_prefix_extend(pre, 0)
    with pytest.raises(ValueError):      # table overflow
        rt.alloc_prefix_extend(pre, 6)
    with pytest.raises(ValueError):      # OOM
        rt.alloc_prefix_extend(pre, 11)
    a = rt.submit_prefixed(pre, PAGE + 1, 0)     # a rider, not a prefix
    rt.admit()
    with pytest.raises(ValueError):      # parent must be a prefix object
        rt.alloc_prefix_extend(a, 1)
    rt.release(pre)
    with pytest.raises(ValueError):      # dead parent
        rt.alloc_prefix_extend(pre, 1)


def test_dead_prefix_detaches_rider_for_full_prefill(rt):
    """A rider admitted after its prefix died must be told to prefill its
    whole prompt (prefix_pages == 0) and must own ALL its pages — the
    prefix-region pages hold no KV, so attention over them would read
    garbage if the engine skipped them (advisor finding)."""
    pre = rt.alloc_prefix(2)
    a = rt.submit_prefixed(pre, prompt_len=2 * PAGE + 5, max_new_tokens=4)
    rt.release(pre)                          # prefix gone before admission
    assert [s for s, _ in rt.admit()] == [a]
    assert rt.prefix_pages(a) == 0
    own = [p for p in rt.block_table(a) if p != 0]
    assert len(own) == 3                     # pages for the FULL prompt
    assert all(rt.page_ref(p) == 1 for p in own)
