"""Mixture-of-experts (mixtral family): HF parity, routing semantics,
expert-parallel sharding, and engine integration.

Parity oracle: transformers' MixtralForCausalLM on a tiny random
checkpoint (fp32, CPU) — the same modeling code that defines the
semantics vLLM serves for the reference (reference inference.py:90-95
delegates architecture correctness to the serving library; here it is
established per-family in-tree, SURVEY §7 hard part 3).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax
import jax.numpy as jnp

TINY_MIXTRAL = dict(
    vocab_size=256, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=512,
    rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=False,
    num_local_experts=4, num_experts_per_tok=2, sliding_window=None,
)


def make_hf_mixtral(tmp_path, **overrides):
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(0)
    cfg = MixtralConfig(**{**TINY_MIXTRAL, **overrides})
    model = MixtralForCausalLM(cfg).eval()
    path = tmp_path / "tiny-mixtral"
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def hf_logits(model, tokens):
    import torch

    with torch.no_grad():
        out = model(torch.tensor(tokens))
    return out.logits.float().numpy()


@pytest.fixture(scope="module")
def mixtral(tmp_path_factory):
    from reval_tpu.models import load_checkpoint

    tmp = tmp_path_factory.mktemp("ckpt")
    model, path = make_hf_mixtral(tmp)
    params, cfg = load_checkpoint(path, dtype="float32")
    return model, params, cfg


class TestMixtralParity:
    def test_config_parsed(self, mixtral):
        _, _, cfg = mixtral
        assert cfg.num_experts == 4 and cfg.num_experts_per_tok == 2
        assert cfg.family == "llama" and cfg.mlp_gated

    def test_expert_weights_stacked(self, mixtral):
        _, params, cfg = mixtral
        assert params["layers"]["moe_gate_w"].shape == (2, 4, 64, 96)
        assert params["layers"]["moe_down_w"].shape == (2, 4, 96, 64)
        assert params["layers"]["router_w"].shape == (2, 64, 4)
        assert "gate_w" not in params["layers"]

    def test_logits_match_hf(self, mixtral):
        from reval_tpu.models import logits_for_tokens

        model, params, cfg = mixtral
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 255, size=(2, 12))
        ours = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        theirs = hf_logits(model, tokens)
        np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-3)

    def test_decode_matches_prefill(self, mixtral):
        from reval_tpu.models import (
            decode_step, init_kv_cache, logits_for_tokens, prefill)

        _, params, cfg = mixtral
        rng = np.random.default_rng(2)
        tokens = rng.integers(0, 255, size=(2, 9))
        full = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))

        cache = init_kv_cache(cfg, 2, 12, dtype=jnp.float32)
        pad = jnp.zeros(2, jnp.int32)
        _, cache = prefill(params, cfg, jnp.asarray(tokens[:, :-1]), pad, cache)
        logits, _ = decode_step(params, cfg, jnp.asarray(tokens[:, -1:]),
                                pad, cache, jnp.int32(8))
        np.testing.assert_allclose(np.asarray(logits), full[:, -1, :],
                                   atol=3e-4, rtol=3e-3)


class TestRouting:
    def _layer(self, cfg, seed=0):
        from reval_tpu.models import init_random_params

        params = init_random_params(cfg, seed=seed, dtype="float32")
        return params, jax.tree_util.tree_map(lambda x: x[0], params["layers"])

    def test_capacity_drop_free_by_default(self):
        import dataclasses

        from reval_tpu.models.model import _moe_capacity
        from reval_tpu.models import ModelConfig

        cfg = ModelConfig(vocab_size=8, hidden_size=8, intermediate_size=8,
                          num_layers=1, num_heads=1, num_kv_heads=1,
                          head_dim=8, num_experts=8)
        # default (factor None): capacity >= s at EVERY size ⇒ no
        # assignment can drop (an expert receives at most one assignment
        # per token), rounded up to the 8-lane tile
        for s in (1, 2, 4, 8, 256, 1000):
            c = _moe_capacity(s, cfg)
            assert c >= s and c % 8 == 0
        # lossy opt-in: bounded (factor × uniform, tiled), not s
        lossy = dataclasses.replace(cfg, moe_capacity_factor=2.0)
        c = _moe_capacity(256, lossy)
        assert c % 8 == 0
        assert 256 * 2 / 8 * 2.0 <= c < 256

    @pytest.mark.parametrize("impl", ["ragged", "dispatch"])
    def test_moe_mlp_equals_dense_per_token_mixture(self, impl):
        """Oracle: loop over tokens, run each token's top-k experts as
        plain dense FFNs, combine with renormalised router weights.
        Both formulations must be exact here (dispatch: cap == s)."""
        from reval_tpu.models import ModelConfig
        from reval_tpu.models.model import _act, _mlp

        cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=24,
                          num_layers=1, num_heads=2, num_kv_heads=2, head_dim=8,
                          num_experts=4, num_experts_per_tok=2, moe_impl=impl)
        params, layer = self._layer(cfg)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 5, 16)), jnp.float32)
        got = np.asarray(_mlp(x, layer, cfg))

        xs = np.asarray(x).reshape(10, 16)
        router = xs @ np.asarray(layer["router_w"])
        probs = np.exp(router - router.max(-1, keepdims=True))
        probs = probs / probs.sum(-1, keepdims=True)
        want = np.zeros_like(xs)
        for i in range(10):
            order = np.argsort(-probs[i])[:2]
            w = probs[i][order] / probs[i][order].sum()
            for e, wi in zip(order, w):
                g = xs[i] @ np.asarray(layer["moe_gate_w"][e])
                u = xs[i] @ np.asarray(layer["moe_up_w"][e])
                act = np.asarray(_act(jnp.asarray(g), cfg))
                want[i] += wi * ((act * u) @ np.asarray(layer["moe_down_w"][e]))
        np.testing.assert_allclose(got.reshape(10, 16), want, atol=1e-5)

    @pytest.mark.parametrize("impl", ["ragged", "dispatch"])
    def test_int8_experts_close_to_float(self, impl):
        from reval_tpu.models import ModelConfig, quantize_params
        from reval_tpu.models.model import _mlp

        cfg = ModelConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                          num_layers=1, num_heads=2, num_kv_heads=2,
                          head_dim=16, num_experts=4, moe_impl=impl)
        params, layer = self._layer(cfg, seed=3)
        qlayer = jax.tree_util.tree_map(lambda x: x[0],
                                        quantize_params(params)["layers"])
        assert qlayer["moe_gate_w"].dtype == jnp.int8
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((1, 6, 32)), jnp.float32)
        f = np.asarray(_mlp(x, layer, cfg))
        q = np.asarray(_mlp(x, qlayer, cfg))
        assert np.max(np.abs(f - q)) < 0.08 * max(1.0, np.max(np.abs(f)))

    def test_dispatch_exact_under_adversarial_skew_by_default(self):
        """Round-4 verdict item 4: with DEFAULT settings (no capacity
        factor) dispatch logits must equal the exact ragged path even
        when the router sends every token to the same two experts — the
        worst case that used to drop assignments past capacity."""
        import dataclasses

        from reval_tpu.models import ModelConfig
        from reval_tpu.models.model import _mlp

        cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=24,
                          num_layers=1, num_heads=2, num_kv_heads=2, head_dim=8,
                          num_experts=4, num_experts_per_tok=2)
        params, layer = self._layer(cfg, seed=11)
        rw = np.zeros(np.asarray(layer["router_w"]).shape, np.float32)
        rw[:, 0] = 10.0          # every token picks experts {0, 1}
        rw[:, 1] = 5.0
        layer = {**layer, "router_w": jnp.asarray(rw)}
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((4, 64, 16)), jnp.float32)  # s=256
        ragged = np.asarray(_mlp(x, layer, cfg))
        disp = np.asarray(_mlp(
            x, layer, dataclasses.replace(cfg, moe_impl="dispatch")))
        np.testing.assert_allclose(ragged, disp, atol=1e-5)

    def test_dispatch_chunking_is_exact(self, monkeypatch):
        """Batches longer than MOE_DISPATCH_CHUNK dispatch chunk-by-chunk;
        routing is per-token, so chunking must not change the output."""
        import dataclasses

        from reval_tpu.models import ModelConfig
        from reval_tpu.models import model as model_mod

        cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=24,
                          num_layers=1, num_heads=2, num_kv_heads=2, head_dim=8,
                          num_experts=4, num_experts_per_tok=2,
                          moe_impl="dispatch")
        params, layer = self._layer(cfg, seed=13)
        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.standard_normal((2, 75, 16)), jnp.float32)  # s=150
        whole = np.asarray(model_mod._mlp(x, layer, cfg))
        monkeypatch.setattr(model_mod, "MOE_DISPATCH_CHUNK", 64)  # 3 chunks
        chunked = np.asarray(model_mod._mlp(x, layer, cfg))
        np.testing.assert_allclose(whole, chunked, atol=1e-6)

    def test_ragged_and_dispatch_agree_beyond_capacity_when_uniform(self):
        """The two formulations agree exactly wherever no assignment
        drops; a skewed router with tiny capacity makes dispatch drop
        while ragged keeps every assignment (documented divergence)."""
        import dataclasses

        from reval_tpu.models import ModelConfig
        from reval_tpu.models.model import _mlp

        cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=24,
                          num_layers=1, num_heads=2, num_kv_heads=2, head_dim=8,
                          num_experts=4, num_experts_per_tok=2)
        params, layer = self._layer(cfg, seed=7)
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((4, 16, 16)), jnp.float32)
        ragged = np.asarray(_mlp(x, layer, cfg))
        disp = np.asarray(_mlp(
            x, layer, dataclasses.replace(cfg, moe_impl="dispatch",
                                          moe_capacity_factor=4.0)))
        np.testing.assert_allclose(ragged, disp, atol=1e-5)


class TestExpertParallel:
    def test_ep_sharded_matches_single_device(self, mixtral):
        from reval_tpu.models import logits_for_tokens
        from reval_tpu.parallel import make_mesh, param_specs, shard_params
        from reval_tpu.parallel.sharding import resolve_moe_impl

        _, params, cfg = mixtral
        mesh = make_mesh(ep=4, tp=2)
        specs = param_specs(params, cfg, mesh)
        assert specs["layers"]["moe_gate_w"][1] == "ep"
        sharded = shard_params(params, cfg, mesh)
        ep_cfg = resolve_moe_impl(cfg, mesh)
        assert ep_cfg.moe_impl == "dispatch"
        rng = np.random.default_rng(5)
        tokens = rng.integers(0, 255, size=(2, 10))
        want = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        got = np.asarray(logits_for_tokens(sharded, ep_cfg, jnp.asarray(tokens)))
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)

    def test_ep_sharded_exact_under_adversarial_skew(self, mixtral):
        """Verdict r4 item 4 (done-criterion): ep-mesh logits ≡ the dense
        single-device oracle under adversarial router skew, with DEFAULT
        settings — no capacity factor, no warning, no dropped tokens."""
        from reval_tpu.models import logits_for_tokens
        from reval_tpu.parallel import make_mesh, shard_params
        from reval_tpu.parallel.sharding import resolve_moe_impl

        _, params, cfg = mixtral
        skewed = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
        rw = np.zeros(np.asarray(params["layers"]["router_w"]).shape,
                      np.float32)
        rw[:, :, 0] = 10.0       # every token → experts {0, 1}, all layers
        rw[:, :, 1] = 5.0
        skewed["layers"] = {**params["layers"],
                            "router_w": jnp.asarray(rw)}
        mesh = make_mesh(ep=4, tp=2)
        sharded = shard_params(skewed, cfg, mesh)
        ep_cfg = resolve_moe_impl(cfg, mesh)
        assert ep_cfg.moe_impl == "dispatch"
        assert ep_cfg.moe_capacity_factor is None
        rng = np.random.default_rng(9)
        tokens = rng.integers(0, 255, size=(2, 48))   # s=96 >> old capacity
        want = np.asarray(logits_for_tokens(skewed, cfg, jnp.asarray(tokens)))
        got = np.asarray(
            logits_for_tokens(sharded, ep_cfg, jnp.asarray(tokens)))
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)

    def test_ep_fallback_replicates_indivisible_experts(self, mixtral):
        from reval_tpu.parallel import make_mesh, param_specs

        _, params, cfg = mixtral
        mesh = make_mesh(ep=3)        # 4 experts % 3 != 0
        specs = param_specs(params, cfg, mesh)
        assert "ep" not in (specs["layers"]["moe_gate_w"] or ())


class TestShardedMoELoad:
    def test_sharded_load_matches_full_load(self, mixtral, tmp_path_factory):
        """The big-model load path (TPUEngine.from_pretrained with tp>1)
        must assemble [L, E, in, out] expert stacks from per-expert HF
        tensors — regression for the '{e}' template KeyError."""
        from reval_tpu.models import load_checkpoint_sharded
        from reval_tpu.parallel import make_mesh

        model, params, cfg = mixtral
        tmp = tmp_path_factory.mktemp("shard_ckpt") / "m"
        model.save_pretrained(tmp, safe_serialization=True)
        mesh = make_mesh(ep=4, tp=2)
        sharded, scfg = load_checkpoint_sharded(tmp, mesh, dtype="float32")
        assert scfg.num_experts == 4
        np.testing.assert_allclose(
            np.asarray(sharded["layers"]["moe_gate_w"]),
            np.asarray(params["layers"]["moe_gate_w"]), atol=0, rtol=0)
        np.testing.assert_allclose(
            np.asarray(sharded["layers"]["router_w"]),
            np.asarray(params["layers"]["router_w"]), atol=0, rtol=0)


class TestMoEEngines:
    def test_static_and_paged_engines_agree(self, mixtral):
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

        _, params, cfg = mixtral
        tok = ByteTokenizer()
        prompts = ["def f(x):", "assert f(1) == "]
        eng = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=128)
        want = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        paged = PagedTPUEngine(params, cfg, tok, max_slots=2, page_size=64,
                               max_seq_len=128)
        got = paged.generate(prompts, max_new_tokens=8, temperature=0.0)
        paged.close()
        assert got == want

    def test_pipelined_engine_runs_moe(self, mixtral):
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.pp_engine import PipelinedTPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.parallel import make_mesh

        _, params, cfg = mixtral
        tok = ByteTokenizer()
        prompts = ["x = 1", "y = 2"]
        plain = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=128)
        want = plain.generate(prompts, max_new_tokens=6, temperature=0.0)
        eng = PipelinedTPUEngine(params, cfg, tok, batch_size=2,
                                 max_seq_len=128, mesh=make_mesh(pp=2, ep=4))
        got = eng.generate(prompts, max_new_tokens=6, temperature=0.0)
        assert got == want
