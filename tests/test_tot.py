"""Trace-of-thoughts mode: dump format, parser taxonomy, two-phase task run
(reference evaluation.py:303-351,455-504,772-828; the parser itself is
in-tree — the reference's external module is absent from its snapshot)."""

import json
import os

import pytest

from reval_tpu.dynamics import CodeSpace, Sandbox
from reval_tpu.tot import (
    EmptyAnswerError,
    TraceOfThoughtsParser,
    ValidationError,
    read_dump,
    trace_dump_path,
    write_oracle_dumps,
    write_trace_dump,
)

CODE = (
    "def f(x):\n"          # 1
    "    y = x + 1\n"      # 2
    "    if y > 2:\n"      # 3
    "        y = y * 10\n" # 4
    "    return y\n"       # 5
)


def _trace(*args):
    space = CodeSpace()
    fn = space.load_function("f", CODE)
    sandbox = Sandbox(fn, timeout=10)
    _, trace = sandbox.run(*args)
    assert sandbox.status == "ok"
    return trace


@pytest.fixture
def dump_dir(tmp_path):
    trace = _trace(5)
    write_trace_dump(tmp_path, "run1", "humaneval", 0, 0,
                     code=CODE, invocation="f(5)", trace=trace)
    return tmp_path


def _parser(base) -> TraceOfThoughtsParser:
    return TraceOfThoughtsParser(base, "humaneval", "run1")


# ---------------------------------------------------------------------------
# format
# ---------------------------------------------------------------------------

def test_dump_roundtrip(dump_dir):
    path = trace_dump_path(dump_dir, "run1", "humaneval", 0, 0)
    header, steps, end = read_dump(path)
    assert header["invocation"] == "f(5)"
    # executed lines (1-indexed): 2, 3, 4, 5
    assert [s["lineno"] for s in steps] == [2, 3, 4, 5]
    # labels mirror the truth channel in an oracle dump
    assert all(s["label"]["lineno"] == s["lineno"] for s in steps)
    assert end["return"] == "60; int"


def test_dump_values_state_grammar(dump_dir):
    path = trace_dump_path(dump_dir, "run1", "humaneval", 0, 0)
    _, steps, _ = read_dump(path)
    # at line 5 (arrival), y has been multiplied
    assert steps[-1]["values"]["y"] == "60; int"


# ---------------------------------------------------------------------------
# parser answers
# ---------------------------------------------------------------------------

def test_parser_coverage(dump_dir):
    p = _parser(dump_dir)
    p.validate_task(0, 0, code=CODE, invocation="f(5)")
    ans, gen = p.process_task(0, 0, "coverage", lineno=4, use_labels=False)
    assert ans is True and "line 4" in gen
    ans, _ = p.process_task(0, 0, "coverage", lineno=99, use_labels=False)
    assert ans is False


def test_parser_path(dump_dir):
    p = _parser(dump_dir)
    ans, _ = p.process_task(0, 0, "path", lineno=3, use_labels=False)
    assert ans == 4
    ans, _ = p.process_task(0, 0, "path", lineno=5, use_labels=False)
    assert ans == -1  # trace ends at the return line
    ans, _ = p.process_task(0, 0, "path", lineno=42, use_labels=False)
    assert ans == -1  # never executed


def test_parser_state_after_semantics(dump_dir):
    p = _parser(dump_dir)
    ans, _ = p.process_task(0, 0, "state", lineno=4, var="y", use_labels=False)
    assert ans == "60; int"  # value *after* line 4 executes
    with pytest.raises(EmptyAnswerError):
        p.process_task(0, 0, "state", lineno=4, var="nope", use_labels=False)


def test_parser_validation_errors(dump_dir):
    p = _parser(dump_dir)
    with pytest.raises(ValidationError):
        p.validate_task(0, 0, code=CODE + "# changed\n", invocation="f(5)")
    with pytest.raises(ValidationError):
        p.validate_task(0, 0, code=CODE, invocation="f(6)")
    with pytest.raises(ValidationError):
        p.validate_task(7, 7, code=CODE, invocation="f(5)")  # missing dump


def test_label_channel_independent_of_model_steps(tmp_path):
    # model simulates the wrong branch; labels still carry ground truth
    trace = _trace(5)
    wrong_steps = [{"lineno": 2, "values": {"y": "6; int"}},
                   {"lineno": 3, "values": {"y": "6; int"}},
                   {"lineno": 5, "values": {"y": "6; int"}}]
    write_trace_dump(tmp_path, "run1", "humaneval", 0, 0,
                     code=CODE, invocation="f(5)", trace=trace, steps=wrong_steps)
    p = _parser(tmp_path)
    labeled, _ = p.process_task(0, 0, "coverage", lineno=4, use_labels=True)
    raw, _ = p.process_task(0, 0, "coverage", lineno=4, use_labels=False)
    assert labeled is True and raw is False


def test_parser_compound_state_vars(tmp_path):
    # probe expressions beyond plain names: tuples, subscripts, self.attr
    code = (
        "def g(xs):\n"
        "    i = 1\n"
        "    j = xs[i]\n"
        "    return (i, j)\n"
    )
    space = CodeSpace()
    fn = space.load_function("g", code)
    sandbox = Sandbox(fn, timeout=10)
    _, trace = sandbox.run([10, 20, 30])
    write_trace_dump(tmp_path, "run1", "humaneval", 1, 0,
                     code=code, invocation="g([10, 20, 30])", trace=trace)
    p = _parser(tmp_path)
    ans, _ = p.process_task(1, 0, "state", lineno=3, var="(i, j)", use_labels=False)
    assert ans == "(1, 20); tuple"
    ans, _ = p.process_task(1, 0, "state", lineno=2, var="xs[0]", use_labels=False)
    assert ans == "10; int"


def test_dump_flattens_self_attributes(tmp_path):
    code = (
        "class C:\n"
        "    def run(self):\n"
        "        self.total = 5\n"
        "        self.total += 2\n"
        "        return self.total\n"
    )
    space = CodeSpace()
    space.load_class("C", code)
    obj = space.ns["C"]()
    sandbox = Sandbox(obj.run, timeout=10)
    _, trace = sandbox.run()
    write_trace_dump(tmp_path, "run1", "humaneval", 2, 0,
                     code=code, invocation="C().run()", trace=trace)
    p = _parser(tmp_path)
    ans, _ = p.process_task(2, 0, "state", lineno=4, var="self.total",
                            use_labels=False)
    assert ans == "7; int"


# ---------------------------------------------------------------------------
# end-to-end: two-phase run over oracle dumps
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("task_name,perfect", [
    ("coverage", True), ("path", True), ("state", True)])
def test_run_tot_oracle_perfect_scores(tmp_path, task_name, perfect):
    from reval_tpu.tasks import TASKS

    n = write_oracle_dumps("humaneval", str(tmp_path / "dumps"), "oracle",
                           max_items=2)
    assert n > 0
    task = TASKS[task_name](
        prompt_type="tot", dataset="humaneval", max_items=2, progress=False,
        model_id="oracle_model", results_dir=str(tmp_path / "gen"),
        tot_base_dir=str(tmp_path / "dumps"), tot_run_name="oracle")
    metrics = task.run()
    assert metrics["total"] > 0
    assert metrics["acc"] == pytest.approx(1.0)
    # valid-test-cases artifact written next to the generation log
    files = os.listdir(task.store.save_dir)
    valid = [f for f in files if "valid_test_cases" in f]
    assert len(valid) == 1
    cases = json.load(open(os.path.join(task.store.save_dir, valid[0])))
    assert len(cases) == metrics["total"]
    # state keys are 4-tuples (task, input, var, line); others 3-tuples
    expected_len = 4 if task_name == "state" else 3
    assert all(len(c) == expected_len for c in cases)


def test_run_tot_invalid_cases_skipped(tmp_path):
    """Dumps for a different invocation fail validation → no valid cases."""
    from reval_tpu.tasks import TASKS

    write_oracle_dumps("humaneval", str(tmp_path / "dumps"), "oracle", max_items=1)
    # corrupt every dump header
    root = tmp_path / "dumps" / "oracle" / "humaneval"
    for f in root.iterdir():
        lines = f.read_text().splitlines()
        header = json.loads(lines[0])
        header["code_sha256"] = "feedfacefeedface"
        f.write_text("\n".join([json.dumps(header)] + lines[1:]) + "\n")
    task = TASKS["coverage"](
        prompt_type="tot", dataset="humaneval", max_items=1, progress=False,
        model_id="m", results_dir=str(tmp_path / "gen"),
        tot_base_dir=str(tmp_path / "dumps"), tot_run_name="oracle")
    metrics = task.run()
    assert metrics["total"] == 0


def test_run_tot_empty_answer_taxonomy(tmp_path):
    """A valid dump whose model channel lacks the probed variable scores as
    EMPTY_ANSWER_ERROR (phase 2), while labels keep the case valid."""
    from reval_tpu.tasks import TASKS
    from reval_tpu.tot.format import read_dump, trace_dump_path

    write_oracle_dumps("humaneval", str(tmp_path / "dumps"), "oracle", max_items=1)
    root = tmp_path / "dumps" / "oracle" / "humaneval"
    for f in root.iterdir():
        lines = [json.loads(l) for l in f.read_text().splitlines()]
        for rec in lines:
            if rec.get("kind") == "step":
                rec["values"] = {}  # model channel forgets all values
        f.write_text("\n".join(json.dumps(r) for r in lines) + "\n")
    task = TASKS["state"](
        prompt_type="tot", dataset="humaneval", max_items=1, progress=False,
        model_id="m", results_dir=str(tmp_path / "gen"),
        tot_base_dir=str(tmp_path / "dumps"), tot_run_name="oracle")
    metrics = task.run()
    assert metrics["total"] > 0 and metrics["acc"] == 0.0
    rows = [json.loads(l) for l in open(task.store.latest("humaneval"))]
    errors = [r.get("error") for row in rows[:-1] for g in row.get("generation", [])
              for r in g.get("results", [])]
    assert errors and all(e == "EMPTY_ANSWER_ERROR" for e in errors)


def test_output_task_rejects_tot(tmp_path):
    from reval_tpu.tasks import TASKS

    with pytest.raises(AssertionError):
        TASKS["output"](prompt_type="tot", dataset="humaneval",
                        model_id="m", tot_base_dir=str(tmp_path), tot_run_name="x")


# ---------------------------------------------------------------------------
# model-driven generation (tot-generate: prompt → text → dump → score)
# ---------------------------------------------------------------------------

class TestTraceGeneration:
    def test_parse_well_formed(self):
        from reval_tpu.tot import parse_trace_generation

        text = ("step 0: line 2 || x = 5; int\n"
                "step 1: line 3 || x = 5; int || y = [1, 2]; list\n"
                "return 6; int\n[/TRACE]\nnoise after stop")
        steps, ret = parse_trace_generation(text)
        assert [s["lineno"] for s in steps] == [2, 3]
        assert steps[1]["values"]["y"] == "[1, 2]; list"   # comma survives
        assert ret == "6; int"

    def test_parse_tolerates_garbage(self):
        from reval_tpu.tot import parse_trace_generation

        steps, ret = parse_trace_generation(
            "I think the program runs like this:\n"
            "step 0: line 2 || x = 5; int\n"
            "??? nonsense line\n"
            "step not-a-number: line 9\n"
            "step 1: line 5 || = orphan; str || y = 6; int\n")
        assert [s["lineno"] for s in steps] == [2, 5]
        assert steps[1]["values"] == {"y": "6; int"}
        assert ret is None

    def test_parse_empty_generation(self):
        from reval_tpu.tot import parse_trace_generation

        steps, ret = parse_trace_generation("The answer is YES")
        assert steps == [] and ret is None

    def test_prompt_round_trip_through_grammar(self):
        """render_trace_text (a perfect model's output) must parse back to
        the exact ground-truth line sequence and values."""
        from reval_tpu.tot import parse_trace_generation
        from reval_tpu.tot.generate import render_trace_text

        trace = _trace(5)
        steps, ret = parse_trace_generation(render_trace_text(trace))
        assert [s["lineno"] for s in steps] == [st.lineno + 1 for st in trace]
        assert ret == "60; int"
        assert steps[1]["values"]["y"] == "6; int"


class _ScriptedTraceBackend:
    """A backend whose generations are real trace-grammar TEXT (perfect or
    corrupted) — drives the full tot-generate path without any oracle dump
    being written directly."""

    def __init__(self, pairs, corrupt=False):
        from reval_tpu.tot.generate import render_trace_text

        self._texts = {}
        for key, (code, invocation, trace) in pairs.items():
            text = render_trace_text(trace)
            if corrupt:
                # model hallucinates: shift every simulated lineno by one
                import re as _re

                text = _re.sub(r"line (\d+)",
                               lambda m: f"line {int(m.group(1)) + 1}", text)
            self._texts[key] = text
        self._queue = [self._texts[k] for k in pairs]

    class config:                       # duck-typed GenerationConfig bits
        stop = ["[/ANSWER]"]

    def infer_many(self, prompts):
        assert len(prompts) == len(self._queue)
        assert all("[TRACE]" in p and "step <n>: line <lineno>" in p
                   for p in prompts)
        return list(self._queue)


def test_tot_generate_end_to_end_scores_without_oracle(tmp_path):
    """Engine-output text → parsed dumps → two-phase tot scoring.  A
    perfect trace-producing model must validate every case and score 100%;
    no oracle dump writer is involved anywhere."""
    from reval_tpu.tasks import TASKS
    from reval_tpu.tot import capture_pairs, generate_trace_dumps

    pairs = capture_pairs("humaneval", max_items=2)
    backend = _ScriptedTraceBackend(pairs)
    n = generate_trace_dumps(backend, "humaneval", str(tmp_path / "dumps"),
                             "model_trace", max_items=2, progress=False)
    assert n == len(pairs) > 0
    task = TASKS["coverage"](
        prompt_type="tot", dataset="humaneval", max_items=2, progress=False,
        model_id="scripted", results_dir=str(tmp_path / "gen"),
        tot_base_dir=str(tmp_path / "dumps"), tot_run_name="model_trace")
    metrics = task.run()
    assert metrics["total"] > 0
    assert metrics["acc"] == pytest.approx(1.0)


def test_tot_generate_corrupted_model_still_scores(tmp_path):
    """A model that hallucinates linenos: labels (ground truth) keep test
    cases valid, the model channel answers wrongly → acc < 1, no crash."""
    from reval_tpu.tasks import TASKS
    from reval_tpu.tot import capture_pairs, generate_trace_dumps

    pairs = capture_pairs("humaneval", max_items=2)
    backend = _ScriptedTraceBackend(pairs, corrupt=True)
    generate_trace_dumps(backend, "humaneval", str(tmp_path / "dumps"),
                         "model_trace", max_items=2, progress=False)
    task = TASKS["coverage"](
        prompt_type="tot", dataset="humaneval", max_items=2, progress=False,
        model_id="scripted", results_dir=str(tmp_path / "gen"),
        tot_base_dir=str(tmp_path / "dumps"), tot_run_name="model_trace")
    metrics = task.run()
    assert metrics["total"] > 0
    assert metrics["acc"] < 1.0


# ---------------------------------------------------------------------------
# adversarial dump fixtures (verdict round-1 weak item 6)
# ---------------------------------------------------------------------------

class TestAdversarialDumps:
    def _write(self, tmp_path, mutate):
        trace = _trace(5)
        path = write_trace_dump(tmp_path, "run1", "humaneval", 0, 0,
                                code=CODE, invocation="f(5)", trace=trace)
        lines = path.read_text().splitlines()
        path.write_text(mutate(lines))
        return _parser(tmp_path)

    def test_wrong_code_digest(self, tmp_path):
        def mutate(lines):
            h = json.loads(lines[0]); h["code_sha256"] = "deadbeef"
            return "\n".join([json.dumps(h)] + lines[1:]) + "\n"
        p = self._write(tmp_path, mutate)
        with pytest.raises(ValidationError):
            p.validate_task(0, 0, code=CODE, invocation="f(5)")

    def test_truncated_mid_record(self, tmp_path):
        def mutate(lines):
            # cut the file inside a JSON record
            return "\n".join(lines[:-2]) + '\n{"kind": "step", "st'
        p = self._write(tmp_path, mutate)
        with pytest.raises(ValidationError):
            p.validate_task(0, 0, code=CODE, invocation="f(5)")

    def test_garbage_values_dont_crash_state(self, tmp_path):
        def mutate(lines):
            out = []
            for line in lines:
                rec = json.loads(line)
                if rec.get("kind") == "step":
                    rec["values"] = {"y": "<<<not a repr", "x": 12345,
                                     "": "empty-name"}
                out.append(json.dumps(rec))
            return "\n".join(out) + "\n"
        p = self._write(tmp_path, mutate)
        # model channel: garbage string comes back verbatim (scored wrong,
        # not crashed); compound vars fail to eval → EmptyAnswerError
        ans, _ = p.process_task(0, 0, "state", lineno=3, var="y",
                                use_labels=False)
        assert ans == "<<<not a repr"
        with pytest.raises(EmptyAnswerError):
            p.process_task(0, 0, "state", lineno=3, var="(y, x)",
                           use_labels=False)

    def test_missing_end_record(self, tmp_path):
        def mutate(lines):
            return "\n".join(l for l in lines
                             if json.loads(l).get("kind") != "end") + "\n"
        p = self._write(tmp_path, mutate)
        ans, _ = p.process_task(0, 0, "coverage", lineno=2, use_labels=False)
        assert ans is True

    def test_non_integer_linenos_skipped(self, tmp_path):
        def mutate(lines):
            out = []
            for line in lines:
                rec = json.loads(line)
                if rec.get("kind") == "step":
                    rec["lineno"] = "four"
                out.append(json.dumps(rec))
            return "\n".join(out) + "\n"
        p = self._write(tmp_path, mutate)
        # schema violation → rejected at load (reader enforces int linenos)
        with pytest.raises(ValidationError):
            p.process_task(0, 0, "coverage", lineno=2, use_labels=False)
