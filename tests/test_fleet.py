"""Fleet runner, analyzer, model zoo, and host-distribution helpers."""

import json
import os

import pytest

from reval_tpu.analyze import analyze_valid_test_cases
from reval_tpu.fleet import FleetRunner
from reval_tpu.models import MODEL_ZOO, zoo_config, zoo_entry
from reval_tpu.parallel.distributed import gather_strings, shard_for_host


# ---------------------------------------------------------------------------
# fleet
# ---------------------------------------------------------------------------

def test_fleet_mock_end_to_end(tmp_path):
    fleet = FleetRunner(dataset="humaneval", prompt_type="direct", repeats=2,
                        mock=True, results_dir=str(tmp_path), progress=False,
                        max_items=2)
    result = fleet.run()
    assert len(result["repeats"]) == 2
    for metrics in result["repeats"]:
        assert set(metrics) == {"coverage", "path", "state", "output"}
    assert "consistency" in result
    # every task wrote one log per repeat, none overwritten
    for task in ("coverage", "path", "state", "output"):
        d = os.path.join(tmp_path, f"{task}@mock_model_direct")
        assert len(os.listdir(d)) == 2


def test_fleet_shared_backend_single_batched_pass(tmp_path):
    """With one shared backend the fleet issues exactly one infer_many per
    repeat, covering all four tasks."""

    class CountingBackend:
        info = "counting_model_direct_temp0.0"
        prompt_type = "direct"

        def __init__(self):
            self.calls = []

        def infer_many(self, prompts):
            self.calls.append(len(prompts))
            return ["[ANSWER]x[/ANSWER]"] * len(prompts)

    backend = CountingBackend()
    fleet = FleetRunner(dataset="humaneval", repeats=1, backend=backend,
                        results_dir=str(tmp_path), progress=False,
                        run_consistency=False, max_items=2)
    result = fleet.run()
    assert len(backend.calls) == 1, "expected one fused inference pass"
    total_jobs = backend.calls[0]
    assert total_jobs > 0
    assert set(result["repeats"][0]) == {"coverage", "path", "state", "output"}


def test_fleet_metrics_match_individual_runs(tmp_path):
    """Fused fleet scoring must equal running each task alone."""
    from reval_tpu.tasks import TASKS

    fleet = FleetRunner(dataset="humaneval", repeats=1, mock=True,
                        results_dir=str(tmp_path / "fleet"), progress=False,
                        run_consistency=False, max_items=2)
    fleet_metrics = fleet.run()["repeats"][0]
    for name in ("coverage", "path", "state", "output"):
        solo = TASKS[name](prompt_type="direct", dataset="humaneval", mock=True,
                           progress=False, max_items=2,
                           results_dir=str(tmp_path / "solo"))
        assert solo.run() == fleet_metrics[name], name


def test_fleet_surfaces_prefix_cache_trailer(tmp_path):
    """A backend exposing a TPU engine with prefix-cache counters gets
    them summarised in the run result (the 'engine stats trailer')."""
    from reval_tpu.inference.tpu.engine import EngineStats

    class FakeEngine:
        def __init__(self):
            self.stats = EngineStats()
            self.stats.prefix_lookup_tokens = 1000
            self.stats.prefix_hit_tokens = 700
            self.stats.prefix_inserted_pages = 9
            self.stats.prefix_evictions = 2

        def prefix_cache_counters(self):
            return {"cached_pages": 9, "pinned_pages": 0, "nodes": 9}

    class EngineBackend:
        info = "engine_model_direct_temp0.0"
        prompt_type = "direct"
        engine = FakeEngine()

        def infer_many(self, prompts):
            return ["[ANSWER]x[/ANSWER]"] * len(prompts)

    fleet = FleetRunner(dataset="humaneval", repeats=1,
                        backend=EngineBackend(), results_dir=str(tmp_path),
                        progress=False, run_consistency=False, max_items=2)
    result = fleet.run()
    trailer = result["prefix_cache"]
    assert trailer["hit_tokens"] == 700
    assert trailer["hit_rate"] == pytest.approx(0.7)
    assert trailer["evictions"] == 2 and trailer["cached_pages"] == 9


def test_fleet_fused_batch_is_task_contiguous(tmp_path):
    """The fused pass must keep each task's prompts contiguous — per-task
    grouping is what feeds the engine's radix prefix cache one template
    run at a time (a global LCP over 4 templates is ~0)."""
    from reval_tpu.tasks import TASKS

    seen: dict[str, list[str]] = {}

    class RecordingBackend:
        info = "recording_model_direct_temp0.0"
        prompt_type = "direct"

        def infer_many(self, prompts):
            seen["prompts"] = list(prompts)
            return ["[ANSWER]x[/ANSWER]"] * len(prompts)

    fleet = FleetRunner(dataset="humaneval", repeats=1,
                        backend=RecordingBackend(), results_dir=str(tmp_path),
                        progress=False, run_consistency=False, max_items=2)
    fleet.run()
    # reconstruct each task's own prompt list; the fused stream must be
    # their concatenation in task order
    expected = []
    for name in ("coverage", "path", "state", "output"):
        task = TASKS[name](model=None, prompt_type="direct",
                           dataset="humaneval", mock=True, max_items=2,
                           progress=False,
                           results_dir=str(tmp_path / "solo"))
        _, jobs = task._plan()
        expected.extend(j.prompt for j in jobs)
    assert seen["prompts"] == expected


# ---------------------------------------------------------------------------
# analyzer
# ---------------------------------------------------------------------------

def test_analyze_valid_test_cases(tmp_path):
    cases = [[11, 0, 3], [11, 0, 5], [11, 1, 3], [12, 0, 7]]
    p = tmp_path / "v.json"
    p.write_text(json.dumps(cases))
    stats = analyze_valid_test_cases(str(p))
    assert stats["num_tasks"] == 2
    assert stats["total_samples"] == 4
    assert stats["avg_input_idxs_per_task"] == pytest.approx(1.5)
    assert stats["avg_sample_per_task"] == pytest.approx(2.0)
    assert stats["avg_sample_per_task_idx"] == pytest.approx(4 / 3)


def test_analyze_state_4tuples(tmp_path):
    cases = [[11, 0, "x", 3], [11, 0, "y", 3]]
    p = tmp_path / "v.json"
    p.write_text(json.dumps(cases))
    stats = analyze_valid_test_cases(str(p))
    assert stats["num_tasks"] == 1 and stats["total_samples"] == 2


# ---------------------------------------------------------------------------
# model zoo
# ---------------------------------------------------------------------------

def test_zoo_covers_reference_model_list():
    # the 13 models of the reference's model_list.txt
    expected = {
        "google/gemma-2b-it", "google/gemma-7b-it",
        "mistralai/Mistral-7B-Instruct-v0.2",
        "codellama/CodeLlama-7b-hf", "codellama/CodeLlama-7b-Instruct-hf",
        "codellama/CodeLlama-7b-Python-hf", "codellama/CodeLlama-13b-Instruct-hf",
        "codellama/CodeLlama-34b-Instruct-hf",
        "bigcode/starcoder2-3b", "bigcode/starcoder2-7b", "bigcode/starcoder2-15b",
        "ise-uiuc/Magicoder-CL-7B", "ise-uiuc/Magicoder-S-CL-7B",
    }
    assert expected <= set(MODEL_ZOO)


def test_zoo_configs_construct():
    for name in MODEL_ZOO:
        cfg = zoo_config(name)
        assert cfg.num_heads % cfg.num_kv_heads == 0, name
        assert cfg.family in ("llama", "gemma", "starcoder2"), name


def test_zoo_aliases():
    assert zoo_entry("deepseek-coder-1.3b").hf_id == "deepseek-ai/deepseek-coder-1.3b-base"
    cfg = zoo_config("codellama-70b")
    assert cfg.num_layers == 80 and cfg.num_kv_heads == 8


# ---------------------------------------------------------------------------
# host distribution
# ---------------------------------------------------------------------------

def test_shard_for_host_partitions_exactly():
    items = list(range(10))
    shards = [shard_for_host(items, i, 3) for i in range(3)]
    # contiguous, ordered, exact cover
    rebuilt = []
    for shard, start in shards:
        assert items[start:start + len(shard)] == shard
        rebuilt.extend(shard)
    assert rebuilt == items
    assert [len(s) for s, _ in shards] == [4, 3, 3]


def test_gather_strings_single_process_identity():
    assert gather_strings(["a", "b"]) == ["a", "b"]
