"""Ring attention: sharded-vs-single-device parity on the virtual 8-device
CPU mesh, GQA support, and agreement with the engine's prefill attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from reval_tpu.ops.attention import prefill_attention
from reval_tpu.parallel import make_mesh
from reval_tpu.parallel.ring_attention import (
    ring_attention_sharded,
    ring_self_attention,
)


def make_qkv(seed=0, b=2, t=256, h=8, h_kv=8, d=32, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), dtype)
    return q, k, v


def test_local_body_matches_prefill_attention():
    q, k, v = make_qkv()
    ref = prefill_attention(q, k, v, pad_len=jnp.zeros(q.shape[0], jnp.int32))
    out = ring_self_attention(q, k, v)      # axis_name=None, one block
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_sharded_ring_matches_single_device(sp):
    q, k, v = make_qkv(seed=1)
    mesh = make_mesh(sp=sp)
    ref = ring_self_attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_sharded_ring_gqa():
    q, k, v = make_qkv(seed=2, h=8, h_kv=2)
    mesh = make_mesh(sp=4)
    ref = prefill_attention(q, k, v, pad_len=jnp.zeros(q.shape[0], jnp.int32))
    out = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_sharded_ring_under_jit_stays_sequence_sharded():
    q, k, v = make_qkv(seed=3, t=512)
    mesh = make_mesh(sp=8)

    @jax.jit
    def run(q, k, v):
        return ring_attention_sharded(q, k, v, mesh)

    out = run(q, k, v)
    # output keeps the sequence sharding: shard-local shape is T/8
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 64, 8, 32)}
    ref = ring_self_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_rejects_indivisible_sequence():
    q, k, v = make_qkv(t=100)
    mesh = make_mesh(sp=8)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention_sharded(q, k, v, mesh)
