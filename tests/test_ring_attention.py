"""Ring attention: sharded-vs-single-device parity on the virtual 8-device
CPU mesh, GQA support, and agreement with the engine's prefill attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

from reval_tpu.ops.attention import prefill_attention
from reval_tpu.parallel import make_mesh
from reval_tpu.parallel.ring_attention import (
    ring_attention_sharded,
    ring_self_attention,
)


def make_qkv(seed=0, b=2, t=256, h=8, h_kv=8, d=32, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), dtype)
    return q, k, v


def test_local_body_matches_prefill_attention():
    q, k, v = make_qkv()
    ref = prefill_attention(q, k, v, pad_len=jnp.zeros(q.shape[0], jnp.int32))
    out = ring_self_attention(q, k, v)      # axis_name=None, one block
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_sharded_ring_matches_single_device(sp):
    q, k, v = make_qkv(seed=1)
    mesh = make_mesh(sp=sp)
    ref = ring_self_attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_sharded_ring_gqa():
    q, k, v = make_qkv(seed=2, h=8, h_kv=2)
    mesh = make_mesh(sp=4)
    ref = prefill_attention(q, k, v, pad_len=jnp.zeros(q.shape[0], jnp.int32))
    out = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_sharded_ring_under_jit_stays_sequence_sharded():
    q, k, v = make_qkv(seed=3, t=512)
    mesh = make_mesh(sp=8)

    @jax.jit
    def run(q, k, v):
        return ring_attention_sharded(q, k, v, mesh)

    out = run(q, k, v)
    # output keeps the sequence sharding: shard-local shape is T/8
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 64, 8, 32)}
    ref = ring_self_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_rejects_indivisible_sequence():
    q, k, v = make_qkv(t=100)
    mesh = make_mesh(sp=8)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention_sharded(q, k, v, mesh)


class TestPadAwareRing:
    def test_pad_masks_keys(self):
        import jax.numpy as jnp
        import numpy as np

        from reval_tpu.ops import prefill_attention
        from reval_tpu.parallel import ring_self_attention

        rng = np.random.default_rng(0)
        b, t, h, hk, d = 2, 16, 4, 2, 8
        q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, t, hk, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, t, hk, d)), jnp.float32)
        pad = jnp.asarray([3, 0], jnp.int32)
        ring = ring_self_attention(q, k, v, pad)
        ref = prefill_attention(q, k, v, pad)
        # compare only real (non-pad) query positions
        np.testing.assert_allclose(np.asarray(ring[0, 3:]),
                                   np.asarray(ref[0, 3:]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(ring[1]), np.asarray(ref[1]),
                                   atol=1e-5)

    def test_pad_aware_sharded(self):
        import jax.numpy as jnp
        import numpy as np

        from reval_tpu.ops import prefill_attention
        from reval_tpu.parallel import make_mesh, ring_attention_sharded

        rng = np.random.default_rng(1)
        b, t, h, d = 2, 32, 4, 8
        q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        pad = jnp.asarray([5, 0], jnp.int32)
        out = ring_attention_sharded(q, k, v, make_mesh(sp=4), pad)
        ref = prefill_attention(q, k, v, pad)
        np.testing.assert_allclose(np.asarray(out[0, 5:]),
                                   np.asarray(ref[0, 5:]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[1]), np.asarray(ref[1]),
                                   atol=1e-5)


class TestSequenceParallelEngine:
    def test_sp_prefill_matches_contiguous(self):
        import jax.numpy as jnp
        import numpy as np

        from reval_tpu.models import (
            ModelConfig, init_kv_cache, init_random_params, prefill)
        from reval_tpu.parallel import make_mesh
        from reval_tpu.parallel.sp_prefill import sequence_parallel_prefill

        cfg = ModelConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                          num_layers=3, num_heads=4, num_kv_heads=2, head_dim=16)
        params = init_random_params(cfg, seed=0, dtype="float32")
        rng = np.random.default_rng(2)
        b, t = 2, 64
        tokens = jnp.asarray(rng.integers(1, 256, (b, t)), jnp.int32)
        pad = jnp.asarray([7, 0], jnp.int32)

        ref_cache = init_kv_cache(cfg, b, t + 4, dtype=jnp.float32)
        want_logits, want_cache = prefill(params, cfg, tokens, pad, ref_cache,
                                          logits_mode="last")
        cache = init_kv_cache(cfg, b, t + 4, dtype=jnp.float32)
        got_logits, got_cache = sequence_parallel_prefill(
            params, cfg, tokens, pad, cache, make_mesh(sp=4, tp=2))
        np.testing.assert_allclose(np.asarray(got_logits),
                                   np.asarray(want_logits),
                                   atol=2e-4, rtol=2e-3)
        # pad positions hold garbage-by-design KV (masked at every read);
        # compare real positions only
        for row, p in enumerate([7, 0]):
            np.testing.assert_allclose(
                np.asarray(got_cache.k[:, row, p:t]),
                np.asarray(want_cache.k[:, row, p:t]),
                atol=2e-4, rtol=2e-3)

    def test_sp_engine_odd_token_budget(self):
        """Cache length t + max_new need not divide sp — the engine must
        round the sp-sharded cache dim up (regression: device_put used to
        reject S=69 over sp=4)."""
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.models import ModelConfig, init_random_params
        from reval_tpu.parallel import make_mesh

        cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=4, num_kv_heads=2,
                          head_dim=16)
        params = init_random_params(cfg, seed=4, dtype="float32")
        tok = ByteTokenizer()
        plain = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512)
        want = plain.generate(["def f():", "x = 1"], max_new_tokens=5,
                              temperature=0.0)
        sp = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512,
                       mesh=make_mesh(sp=4))
        got = sp.generate(["def f():", "x = 1"], max_new_tokens=5,
                          temperature=0.0)
        assert got == want

    def test_sp_engine_generation_matches_plain(self):
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.models import ModelConfig, init_random_params
        from reval_tpu.parallel import make_mesh

        cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=4, num_kv_heads=2,
                          head_dim=16)
        params = init_random_params(cfg, seed=3, dtype="float32")
        tok = ByteTokenizer()
        prompts = ["def longctx(x):\n    " + "y = x + 1\n    " * 8,
                   "assert longctx("]
        plain = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512)
        want = plain.generate(prompts, max_new_tokens=8, temperature=0.0)
        sp = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512,
                       mesh=make_mesh(sp=4, tp=2))
        got = sp.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == want

    def test_sp_engine_with_dp_axis(self):
        """dp x sp x tp composition: batch stays data-parallel through the
        ring path (regression: the sp constraint used to replicate batch
        over dp, running dp-fold redundant prefill)."""
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.models import ModelConfig, init_random_params
        from reval_tpu.parallel import make_mesh

        cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=4, num_kv_heads=2,
                          head_dim=16)
        params = init_random_params(cfg, seed=5, dtype="float32")
        tok = ByteTokenizer()
        prompts = ["def f(x):", "x = 1", "y = 2", "assert f("]
        plain = TPUEngine(params, cfg, tok, batch_size=4, max_seq_len=512)
        want = plain.generate(prompts, max_new_tokens=8, temperature=0.0)
        eng = TPUEngine(params, cfg, tok, batch_size=4, max_seq_len=512,
                        mesh=make_mesh(dp=2, sp=2, tp=2))
        got = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == want


class TestWindowedAndSoftcappedRing:
    """Round-4: the two former sp blockers (sliding windows, score
    softcapping) now ride the ring masks — oracle is the engine's dense
    prefill_attention with identical parameters."""

    def test_window_matches_dense(self):
        q, k, v = make_qkv(seed=5, t=64, h=4, h_kv=2, d=16)
        pad = jnp.zeros(q.shape[0], jnp.int32)
        ref = prefill_attention(q, k, v, pad, window=24)
        out = ring_attention_sharded(q, k, v, make_mesh(sp=4), pad,
                                     jnp.int32(24))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_window_smaller_than_block_and_pad(self):
        # window INSIDE one ring block + left padding: the distance mask
        # and the pad mask must compose
        q, k, v = make_qkv(seed=6, t=64, h=4, h_kv=4, d=16)
        pad = jnp.asarray([9, 0], jnp.int32)
        ref = prefill_attention(q, k, v, pad, window=5)
        out = ring_attention_sharded(q, k, v, make_mesh(sp=4), pad,
                                     jnp.int32(5))
        np.testing.assert_allclose(np.asarray(out[0, 9:]),
                                   np.asarray(ref[0, 9:]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[1]), np.asarray(ref[1]),
                                   atol=1e-5)

    def test_softcap_matches_dense(self):
        q, k, v = make_qkv(seed=7, t=64, h=4, h_kv=2, d=16)
        pad = jnp.zeros(q.shape[0], jnp.int32)
        ref = prefill_attention(q, k, v, pad, softcap=30.0)
        out = ring_attention_sharded(q, k, v, make_mesh(sp=4), pad,
                                     softcap=30.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_window_and_softcap_compose(self):
        q, k, v = make_qkv(seed=8, t=64, h=4, h_kv=2, d=16)
        pad = jnp.asarray([3, 0], jnp.int32)
        ref = prefill_attention(q, k, v, pad, window=16, softcap=20.0)
        out = ring_attention_sharded(q, k, v, make_mesh(sp=4), pad,
                                     jnp.int32(16), softcap=20.0)
        np.testing.assert_allclose(np.asarray(out[0, 3:]),
                                   np.asarray(ref[0, 3:]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[1]), np.asarray(ref[1]),
                                   atol=1e-5)

    def test_sp_engine_sliding_window_model(self):
        """Mistral-shaped long-context: the sp engine generates
        identically to the plain engine on a uniformly-windowed model."""
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.models import ModelConfig, init_random_params
        from reval_tpu.parallel import make_mesh

        cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                          hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=4, num_kv_heads=2,
                          head_dim=16, sliding_window=24)
        params = init_random_params(cfg, seed=9, dtype="float32")
        tok = ByteTokenizer()
        prompts = ["def win(x):\n    " + "y = x * 2\n    " * 8,
                   "assert win("]
        plain = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512)
        want = plain.generate(prompts, max_new_tokens=8, temperature=0.0)
        sp = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512,
                       mesh=make_mesh(sp=4))
        got = sp.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == want

    def test_sp_engine_gemma2_style_model(self):
        """Softcap + alternating local/global windows + sandwich norms
        (the gemma-2 surface) through the sp engine."""
        from reval_tpu.inference.tpu.engine import TPUEngine
        from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
        from reval_tpu.models import ModelConfig, init_random_params
        from reval_tpu.parallel import make_mesh

        cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                          hidden_size=64, intermediate_size=128,
                          num_layers=4, num_heads=4, num_kv_heads=2,
                          head_dim=16, sliding_window=16,
                          alt_sliding=True,
                          attn_softcap=50.0, final_softcap=30.0,
                          use_post_norms=True)
        params = init_random_params(cfg, seed=10, dtype="float32")
        tok = ByteTokenizer()
        prompts = ["class Gem:\n    " + "a = 1\n    " * 10, "g = Gem()"]
        plain = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512)
        want = plain.generate(prompts, max_new_tokens=8, temperature=0.0)
        sp = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=512,
                       mesh=make_mesh(sp=4, tp=2))
        got = sp.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == want
