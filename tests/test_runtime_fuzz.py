"""Property fuzz for the native scheduler: random op sequences must keep
the allocator's invariants.

The C++ runtime (runtime/native/runtime.cpp) owns free-page accounting,
block tables, slot assignment, preemption, and refcounted prefix sharing.
The unit tests in test_runtime*.py pin known scenarios; this fuzz drives
long random interleavings of submit / admit / advance / preempt / fork /
release (seeded — failures reproduce) and checks after every step:

- no live sequence's block table points outside the pool, at the trash
  page 0, or at a page owned by an unrelated sequence;
- pages referenced by exactly the sequences that own them (prefix pages:
  refcount == riders + the prefix object itself);
- a released/retired sequence's pages return to the free pool — nothing
  leaks (conservation);
- running slots are unique and within max_slots.
"""

from __future__ import annotations

import numpy as np
import pytest

from reval_tpu.runtime import PagedRuntime

NUM_PAGES = 32
PAGE = 16
SLOTS = 4
SPAN = 8          # max pages per seq


class Harness:
    def __init__(self, seed: int):
        self.rng = np.random.default_rng(seed)
        self.rt = PagedRuntime(NUM_PAGES, PAGE, SLOTS, SPAN)
        self.running: dict[int, dict] = {}     # seq_id -> {len}
        self.waiting: set[int] = set()
        self.prefixes: dict[int, int] = {}     # prefix_id -> n_pages
        self.released_prefixes: set[int] = set()

    def close(self):
        self.rt.close()

    # -- op pool ---------------------------------------------------------
    def op_submit(self):
        plen = int(self.rng.integers(1, SPAN * PAGE // 2))
        new = int(self.rng.integers(1, PAGE))
        seq = self.rt.submit(plen, new)
        self.waiting.add(seq)

    def op_submit_prefixed(self):
        live = [p for p in self.prefixes if p not in self.released_prefixes]
        if not live:
            return
        prefix = int(self.rng.choice(live))
        own = int(self.rng.integers(1, 2 * PAGE))
        seq = self.rt.submit_prefixed(
            prefix, self.prefixes[prefix] * PAGE + own, int(self.rng.integers(1, PAGE)))
        self.waiting.add(seq)

    def op_alloc_prefix(self):
        if len(self.prefixes) >= 3:
            return
        n = int(self.rng.integers(1, 3))
        pid = self.rt.alloc_prefix(n)
        if pid >= 0:
            self.prefixes[pid] = n

    def op_extend_prefix(self):
        # radix-style chains: a child prefix shares the parent's pages
        live = [p for p in self.prefixes if p not in self.released_prefixes]
        if not live or len(self.prefixes) >= 6:
            return
        parent = int(self.rng.choice(live))
        try:
            child = self.rt.alloc_prefix_extend(parent, 1)
        except ValueError:
            return                       # OOM/overflow: fine under fuzz
        self.prefixes[child] = self.prefixes[parent] + 1

    def op_admit(self):
        for seq, slot in self.rt.admit():
            assert seq in self.waiting, "admitted a sequence never submitted"
            self.waiting.discard(seq)
            self.running[seq] = {"slot": slot}

    def op_advance(self):
        if not self.running:
            return
        seq = int(self.rng.choice(list(self.running)))
        self.rt.advance(seq, int(self.rng.integers(1, PAGE)))
        # advance may preempt victims (returns None) — runtime moves them
        # back to waiting; sync our mirror from slot_of
        for s in list(self.running):
            if self.rt.slot_of(s) < 0:
                self.running.pop(s)
                self.waiting.add(s)

    def op_preempt(self):
        if not self.running:
            return
        seq = int(self.rng.choice(list(self.running)))
        self.rt.preempt(seq, max(1, self.rt.seq_len(seq)))
        self.running.pop(seq)
        self.waiting.add(seq)

    def op_release(self):
        pool = list(self.running) + list(self.waiting)
        if not pool:
            return
        seq = int(self.rng.choice(pool))
        self.rt.release(seq)
        self.running.pop(seq, None)
        self.waiting.discard(seq)

    def op_release_prefix(self):
        live = [p for p in self.prefixes if p not in self.released_prefixes]
        if not live:
            return
        pid = int(self.rng.choice(live))
        self.rt.release(pid)
        self.released_prefixes.add(pid)

    # -- invariants ------------------------------------------------------
    def check(self):
        owners: dict[int, list[int]] = {}
        for seq in self.running:
            slot = self.rt.slot_of(seq)
            assert 0 <= slot < SLOTS, f"slot {slot} out of range"
            table = self.rt.block_table(seq)
            ln = self.rt.seq_len(seq)
            used = (ln + PAGE - 1) // PAGE
            for page in table[:used]:
                assert 0 < page < NUM_PAGES, f"page {int(page)} out of pool"
                owners.setdefault(int(page), []).append(seq)
        # slots unique
        slots = [self.rt.slot_of(s) for s in self.running]
        assert len(slots) == len(set(slots)), f"slot collision: {slots}"
        # a page shared by two sequences must be refcounted > 1 (prefix
        # sharing or fork); the runtime exposes per-page refcounts
        for page, seqs in owners.items():
            ref = self.rt.page_ref(page)
            assert ref >= len(seqs), (
                f"page {page} owned by {seqs} but refcount {ref}")
        # conservation: free pages never exceed the pool (minus trash)
        free = self.rt.free_pages
        assert 0 <= free <= NUM_PAGES - 1


@pytest.mark.parametrize("seed", range(8))
def test_random_op_sequences_keep_invariants(seed):
    h = Harness(seed)
    ops = [h.op_submit, h.op_submit_prefixed, h.op_alloc_prefix, h.op_admit,
           h.op_extend_prefix, h.op_advance, h.op_advance, h.op_preempt,
           h.op_release, h.op_release_prefix]
    try:
        for step in range(400):
            op = ops[int(h.rng.integers(0, len(ops)))]
            op()
            h.check()
    finally:
        h.close()


def test_fuzz_eventually_drains():
    """After any random prefix of ops, releasing everything returns the
    pool to fully free — no leaked pages."""
    h = Harness(99)
    ops = [h.op_submit, h.op_submit_prefixed, h.op_alloc_prefix, h.op_admit,
           h.op_extend_prefix, h.op_advance, h.op_preempt]
    try:
        for _ in range(200):
            ops[int(h.rng.integers(0, len(ops)))]()
        for seq in list(h.running) + list(h.waiting):
            h.rt.release(seq)
        for pid in h.prefixes:
            if pid not in h.released_prefixes:
                h.rt.release(pid)
        assert h.rt.free_pages == NUM_PAGES - 1   # all but the trash page
    finally:
        h.close()
