"""Sharded checkpoint loading (models/sharded_loader.py): every shard read
straight from safetensors must equal the full-load-then-shard path, with
the production sharding rules applied — on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402


@pytest.fixture(scope="module")
def llama_checkpoint(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    path = tmp_path_factory.mktemp("ckpt") / "tiny-llama-sharded"
    torch.manual_seed(11)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=3, num_attention_heads=8,
                      num_key_value_heads=8)
    LlamaForCausalLM(cfg).eval().save_pretrained(path, safe_serialization=True)
    return path


def test_sharded_load_matches_full_load(llama_checkpoint):
    from reval_tpu.models import load_checkpoint, load_checkpoint_sharded
    from reval_tpu.parallel import make_mesh, shard_params

    mesh = make_mesh(tp=4, dp=2)
    full, cfg_full = load_checkpoint(llama_checkpoint, dtype="float32")
    sharded_ref = shard_params(full, cfg_full, mesh)
    got, cfg = load_checkpoint_sharded(llama_checkpoint, mesh, dtype="float32")

    assert cfg.num_layers == cfg_full.num_layers
    ref_leaves = jax.tree_util.tree_flatten_with_path(sharded_ref)[0]
    got_tree = dict(jax.tree_util.tree_flatten_with_path(got)[0])
    assert len(ref_leaves) == len(got_tree)
    for path, ref_leaf in ref_leaves:
        got_leaf = got_tree[path]
        np.testing.assert_allclose(np.asarray(got_leaf), np.asarray(ref_leaf),
                                   rtol=1e-6, atol=1e-6,
                                   err_msg=f"mismatch at {path}")
        assert got_leaf.sharding.spec == ref_leaf.sharding.spec, path


def test_sharded_load_runs_forward(llama_checkpoint):
    """Sharded-loaded params drive a jitted forward to the same logits as
    the full load."""
    from reval_tpu.models import (
        load_checkpoint,
        load_checkpoint_sharded,
        logits_for_tokens,
    )
    from reval_tpu.parallel import make_mesh

    mesh = make_mesh(tp=8)
    full, cfg = load_checkpoint(llama_checkpoint, dtype="float32")
    got, cfg2 = load_checkpoint_sharded(llama_checkpoint, mesh, dtype="float32")
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    ref = np.asarray(logits_for_tokens(full, cfg, tokens))
    out = np.asarray(logits_for_tokens(got, cfg2, tokens))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_engine_from_pretrained_tp_uses_sharded_load(llama_checkpoint):
    """The tp>1 engine construction path loads shard-direct and generates
    the same text as an unsharded engine."""
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

    prompts = ["def f(x):", "y = "]
    solo = PagedTPUEngine.from_pretrained(
        llama_checkpoint, dtype="float32", max_slots=2, max_seq_len=512,
        tokenizer=ByteTokenizer())
    want = solo.generate(prompts, max_new_tokens=6, temperature=0.0)
    solo.close()
    eng = PagedTPUEngine.from_pretrained(
        llama_checkpoint, dtype="float32", tp_size=4, max_slots=2,
        max_seq_len=512, tokenizer=ByteTokenizer())
    assert "tp" in str(eng.params["layers"]["q_w"].sharding.spec)
    got = eng.generate(prompts, max_new_tokens=6, temperature=0.0)
    eng.close()
    assert got == want


def test_sharded_load_rejects_int8(llama_checkpoint):
    from reval_tpu.models import load_checkpoint_sharded
    from reval_tpu.parallel import make_mesh

    with pytest.raises(ValueError, match="int8"):
        load_checkpoint_sharded(llama_checkpoint, make_mesh(tp=8),
                                dtype="int8")

def test_sharded_int4_load_matches_its_dequantised_oracle(llama_checkpoint):
    """dtype="int4" through the shard-direct path (the 34B-on-v5e-8
    flow): weights land int4 + group scales land sharded, and greedy
    generation equals an engine fed the dequantised weights — proving
    the shard-local quantization arithmetic end to end."""
    import jax.numpy as jnp

    from reval_tpu.inference.tpu.engine import TPUEngine
    from reval_tpu.models import load_checkpoint_sharded
    from reval_tpu.models.quant import dequantize_params, is_quantized
    from reval_tpu.parallel import make_mesh

    mesh = make_mesh(tp=2)
    params, cfg = load_checkpoint_sharded(llama_checkpoint, mesh, dtype="int4")
    assert is_quantized(params)
    assert params["layers"]["q_w"].dtype == jnp.int4
    assert params["layers"]["q_w_gscale"].ndim == 3
    assert params["embed"].dtype == jnp.bfloat16

    class _Tok:           # the fixture checkpoint ships no tokenizer files
        eos_id, pad_id = 127, 0

        def encode(self, text):
            return [ord(c) % 120 + 1 for c in text]

        def decode(self, ids):
            return "".join(chr(32 + (int(i) % 90)) for i in ids)

    tok = _Tok()
    prompts = ["def f(x):", "x = 1"]
    eng_q = TPUEngine(params, cfg, tok, batch_size=2, max_seq_len=256,
                      mesh=mesh)
    got = eng_q.generate(prompts, max_new_tokens=8, temperature=0.0)
    oracle = TPUEngine(dequantize_params(params, jnp.bfloat16), cfg, tok,
                       batch_size=2, max_seq_len=256, mesh=mesh)
    want = oracle.generate(prompts, max_new_tokens=8, temperature=0.0)
    assert got == want
