"""Dynamics-layer tests, modeled on the reference test strategy (SURVEY §4)."""

import pickle

import pytest

from reval_tpu.dynamics import (
    ClassFactory,
    CodeSpace,
    ExecutionTrace,
    FunctionFactory,
    Nil,
    Sandbox,
)


class TestNil:
    def test_identity_and_inequality(self):
        assert Nil != None  # noqa: E711
        assert Nil != 0
        assert Nil != False  # noqa: E712
        a = Nil
        assert a is Nil
        assert a == Nil

    def test_pickle_roundtrip(self):
        assert pickle.loads(pickle.dumps(Nil)) is Nil

    def test_copy_roundtrip(self):
        import copy

        assert copy.copy(Nil) is Nil
        assert copy.deepcopy(Nil) is Nil

    def test_falsy_repr(self):
        assert not Nil
        assert repr(Nil) == "Nil"


class TestFactories:
    def test_function_factory(self):
        code = "def f(x):\n\treturn x**2"
        fn = FunctionFactory.create("f", code)
        assert fn(2) == 4
        assert fn.__doc__ == code

    def test_class_factory(self):
        code = "class A:\n\tdef __init__(self, x):\n\t\tself.x = x\n\tdef f(self):\n\t\treturn self.x**2"
        cls = ClassFactory.create("A", code)
        assert cls(2).f() == 4
        assert cls.__doc__ == code

    def test_namespace_isolation(self):
        FunctionFactory.create("f", "def f():\n\treturn 1")
        g = FunctionFactory.create("g", "def g():\n\treturn 'f' in dir()")
        # separate CodeSpaces: the second blob does not see the first's f
        space = CodeSpace()
        space.load_function("h", "def h():\n\treturn 2")
        assert "f" not in space.ns


class TestSandboxBasics:
    def test_square(self):
        fn = FunctionFactory.create("f", "def f(x):\n\treturn x**2")
        sandbox = Sandbox(fn)
        result, states = sandbox.run(2)
        assert result == 4
        assert states.get_return(1) == 4
        assert states.get_local(1, "x") == [2]
        assert states.get_exception(1) is Nil
        assert not states.get_coverage(0)
        assert states.get_coverage(1)
        assert -1 in states.get_next_line(1)
        assert sandbox.status == "ok"

    def test_uncovered_next_line_is_minus_one(self):
        fn = FunctionFactory.create("f", "def f(x):\n\tif x > 0:\n\t\treturn 1\n\telse:\n\t\treturn 2")
        _, states = Sandbox(fn).run(5)
        assert states.get_next_line(4) == {-1}  # else branch not taken
        assert states.get_coverage(2)
        assert not states.get_coverage(4)

    def test_loop_collects_values_across_iterations(self):
        code = "def f(n):\n\ts = 0\n\tfor i in range(n):\n\t\ts = s + i\n\treturn s"
        fn = FunctionFactory.create("f", code)
        result, states = Sandbox(fn).run(3)
        assert result == 3
        # after-semantics: values of s after line 3 executes each time
        assert states.get_local(3, "s") == [0, 1, 3]
        # successors of the loop body line include the loop header
        assert 2 in states.get_next_line(3)

    def test_helper_function_traced(self):
        code = "def f(x):\n\treturn x**2\ndef g(x):\n\ta = f(x)\n\treturn a"
        fn = FunctionFactory.create("g", code)
        result, states = Sandbox(fn).run(2)
        assert result == 4
        assert states.get_return(1) == 4
        assert states.get_return(4) == 4
        assert states.get_coverage(1)

    def test_nested_function_traced(self):
        code = "def g(x):\n\tdef f(x):\n\t\ty = x**2\n\t\treturn y\n\ta = f(x)\n\treturn a"
        fn = FunctionFactory.create("g", code)
        result, states = Sandbox(fn).run(2)
        assert result == 4
        assert 4 in states.get_local(2, "y")

    def test_exception_recorded_and_status(self):
        fn = FunctionFactory.create("f", "def f(x):\n\treturn 1 // x")
        sandbox = Sandbox(fn)
        result, states = sandbox.run(0)
        assert sandbox.status.startswith("exception:")
        assert states.get_exception(1) is ZeroDivisionError

    def test_timeout(self):
        fn = FunctionFactory.create("f", "def f():\n\twhile True:\n\t\tpass")
        sandbox = Sandbox(fn, timeout=0.2)
        sandbox.run()
        assert sandbox.status == "timed out"

    def test_io_swallowed(self, capsys):
        fn = FunctionFactory.create("f", "def f():\n\tprint('loud')\n\treturn 1")
        result, _ = Sandbox(fn).run()
        assert result == 1
        assert "loud" not in capsys.readouterr().out

    def test_rerun_resets_state(self):
        fn = FunctionFactory.create("f", "def f(x):\n\treturn x + 1")
        sandbox = Sandbox(fn)
        sandbox.run(1)
        result, states = sandbox.run(10)
        assert result == 11
        assert states.get_local(1, "x") == [10]


CLASS_CODE = """class Greeter:
    def __init__(self, name):
        self.name = name
        self.count = 0

    def greet(self, request):
        method = request["method"]
        self.count = self.count + 1
        if method == "GET":
            return "hello " + self.name
        return "bye"
"""

TEST_CODE = """import unittest

class GreeterTestGreet(unittest.TestCase):
    def test_greet(self):
        g = Greeter("ada")
        request = {"method": "GET"}
        out = g.greet(request)
        self.assertEqual(out, "hello ada")
"""


class TestClassEvalFlow:
    def _make_test_class(self):
        from reval_tpu.datasets.dreval import ClassEvalHooks

        space = CodeSpace()
        space.load_class("Greeter", CLASS_CODE)
        classes = space.load_test_classes(
            "Greeter",
            CLASS_CODE,
            TEST_CODE,
            ClassEvalHooks.name_pattern,
            ClassEvalHooks.validation,
            ClassEvalHooks.postprocess,
        )
        assert len(classes) == 1
        return classes[0]

    def test_traced_class_under_test(self):
        tcls = self._make_test_class()
        obj = tcls()
        sandbox = Sandbox(obj.dreval_test)
        _, states = sandbox.run()
        assert sandbox.status == "ok"
        # linenos are 0-indexed into CLASS_CODE
        assert states.get_coverage(6)  # method = request["method"]
        assert 7 in states.get_next_line(6)
        assert "GET" in states.get_local(6, "method")
        assert "GET" in states.get_subscript(6, "request", '"method"')
        assert states.get_attr(6, "self", "name")[0] == "ada"
        assert "GET" in states.interpret_var(6, "method")
        assert "GET" in states.interpret_var(6, 'request["method"]')
        assert "ada" in states.interpret_var(9, "self.name")

    def test_interpret_var_shapes(self):
        tcls = self._make_test_class()
        obj = tcls()
        _, states = Sandbox(obj.dreval_test).run()
        assert states.interpret_var(6, "self.count") == [0]
        assert states.interpret_var(7, "self.count") == [1]
        assert states.interpret_var(6, "(method, self.count)") == [("GET", 0)]
        assert states.interpret_var(99, "method") is Nil
        assert states.interpret_var(6, "nonexistent") is Nil

    def test_output_predictor_resolves_class_under_test(self):
        from reval_tpu.dynamics import FunctionFactory

        tcls = self._make_test_class()
        generated = 'g = Greeter("ada")\nassertEqual(g.greet({"method": "GET"}), "hello ada")'
        FunctionFactory.create_from_answer(generated, tcls)
        obj = tcls()
        obj.dreval_output_pred()  # must not raise: names resolve, assertion holds

        bad = 'g = Greeter("ada")\nassertEqual(g.greet({"method": "GET"}), "WRONG")'
        FunctionFactory.create_from_answer(bad, tcls)
        obj = tcls()
        with pytest.raises(AssertionError):
            obj.dreval_output_pred()

    def test_test_method_not_traced(self):
        tcls = self._make_test_class()
        obj = tcls()
        _, states = Sandbox(obj.dreval_test).run()
        # trace must only contain linenos that exist within CLASS_CODE body
        assert max(states.trace) < len(CLASS_CODE.split("\n"))
        # local 'g' lives in the (untraced) test frame, not the trace
        assert states.get_local(4, "g") is Nil


class TestExecutionTrace:
    def test_merge_same_line_events(self):
        tr = ExecutionTrace()
        tr.record(3, "locals", {"x": 1}, "line3")
        tr.record(3, "return", 7, "line3")
        assert len(tr) == 1
        assert tr.get_return(3) == 7
        assert tr.get_local(3, "x") == [{"x": 1}["x"]]

    def test_to_json(self):
        tr = ExecutionTrace()
        tr.record(0, "locals", {"s": {2, 1}}, "l0")
        tr.record(1, "exception", ValueError, "l1")
        docs = tr.to_json()
        assert set(docs[0]["locals"]["s"]) == {1, 2}
        assert docs[1]["exception"] == "ValueError"
