"""Model parity tests: our JAX forward vs transformers' reference
implementations, on tiny random checkpoints (float32, CPU).

This is the accuracy-parity strategy from SURVEY §7 hard-part 3: no
checkpoint downloads here (zero egress), so parity is established
per-architecture against HF's CPU modeling code, which is the same code
that defines the reference's vLLM weights' semantics.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax.numpy as jnp

TINY_LLAMA = dict(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=512,
    rope_theta=10000.0, rms_norm_eps=1e-6, tie_word_embeddings=False,
)


def make_hf_llama(tmp_path, **overrides):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    cfg = LlamaConfig(**{**TINY_LLAMA, **overrides})
    model = LlamaForCausalLM(cfg).eval()
    path = tmp_path / "tiny-llama"
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def hf_logits(model, tokens):
    import torch

    with torch.no_grad():
        out = model(torch.tensor(tokens))
    return out.logits.float().numpy()


class TestLlamaParity:
    @pytest.fixture(scope="class")
    def setup(self, tmp_path_factory):
        from reval_tpu.models import load_checkpoint

        tmp = tmp_path_factory.mktemp("ckpt")
        model, path = make_hf_llama(tmp)
        params, cfg = load_checkpoint(path, dtype="float32")
        return model, params, cfg

    def test_logits_match_hf(self, setup):
        from reval_tpu.models import logits_for_tokens

        model, params, cfg = setup
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 255, size=(2, 12))
        ours = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        theirs = hf_logits(model, tokens)
        np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)

    def test_prefill_respects_left_padding(self, setup):
        from reval_tpu.models import init_kv_cache, prefill

        model, params, cfg = setup
        rng = np.random.default_rng(1)
        raw = rng.integers(0, 255, size=(1, 8))
        pad = 4
        padded = np.concatenate([np.zeros((1, pad), int), raw], axis=1)
        cache = init_kv_cache(cfg, 1, padded.shape[1], dtype=jnp.float32)
        logits_padded, _ = prefill(params, cfg, jnp.asarray(padded),
                                   jnp.asarray([pad], jnp.int32), cache)
        cache0 = init_kv_cache(cfg, 1, raw.shape[1], dtype=jnp.float32)
        logits_raw, _ = prefill(params, cfg, jnp.asarray(raw),
                                jnp.asarray([0], jnp.int32), cache0)
        np.testing.assert_allclose(
            np.asarray(logits_padded[:, pad:, :]), np.asarray(logits_raw),
            atol=2e-4, rtol=2e-3,
        )

    def test_decode_matches_prefill(self, setup):
        """Token-by-token decode must reproduce the full-sequence logits."""
        from reval_tpu.models import decode_step, init_kv_cache, prefill

        model, params, cfg = setup
        rng = np.random.default_rng(2)
        tokens = rng.integers(0, 255, size=(2, 10))
        full = np.asarray(
            __import__("reval_tpu.models", fromlist=["logits_for_tokens"]).logits_for_tokens(
                params, cfg, jnp.asarray(tokens))
        )
        prompt_len = 6
        cache = init_kv_cache(cfg, 2, 16, dtype=jnp.float32)
        pad = jnp.zeros(2, jnp.int32)
        logits, cache = prefill(params, cfg, jnp.asarray(tokens[:, :prompt_len]), pad, cache)
        np.testing.assert_allclose(np.asarray(logits), full[:, :prompt_len],
                                   atol=2e-4, rtol=2e-3)
        for step in range(prompt_len, tokens.shape[1]):
            step_logits, cache = decode_step(
                params, cfg, jnp.asarray(tokens[:, step:step + 1]), pad, cache,
                jnp.int32(step))
            np.testing.assert_allclose(np.asarray(step_logits), full[:, step],
                                       atol=3e-4, rtol=3e-3)

    def test_gqa_grouping(self, setup):
        _, params, cfg = setup
        assert cfg.num_kv_heads == 2 and cfg.num_heads == 4
        assert params["layers"]["k_w"].shape[-1] == cfg.num_kv_heads * cfg.head_dim


class TestMistralParity:
    def test_logits_match_hf(self, tmp_path):
        import torch
        from transformers import MistralConfig, MistralForCausalLM

        from reval_tpu.models import load_checkpoint, logits_for_tokens

        torch.manual_seed(1)
        cfg_hf = MistralConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512, sliding_window=None,
        )
        model = MistralForCausalLM(cfg_hf).eval()
        path = tmp_path / "tiny-mistral"
        model.save_pretrained(path, safe_serialization=True)
        params, cfg = load_checkpoint(path, dtype="float32")
        tokens = np.random.default_rng(3).integers(0, 255, size=(2, 9))
        ours = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        theirs = hf_logits(model, tokens)
        np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)


class TestGemmaParity:
    """Gemma family quirks: (1+w) RMSNorm weights, sqrt(hidden) embedding
    scale, tied lm head, GeLU-gated MLP (reference zoo: gemma 2b/7b)."""

    def test_logits_match_hf(self, tmp_path):
        import torch
        from transformers import GemmaConfig, GemmaForCausalLM

        from reval_tpu.models import load_checkpoint, logits_for_tokens

        torch.manual_seed(2)
        cfg_hf = GemmaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
            head_dim=16, max_position_embeddings=512,
            hidden_activation="gelu_pytorch_tanh",
        )
        model = GemmaForCausalLM(cfg_hf).eval()
        path = tmp_path / "tiny-gemma"
        model.save_pretrained(path, safe_serialization=True)
        params, cfg = load_checkpoint(path, dtype="float32")
        assert cfg.family == "gemma" and cfg.tie_word_embeddings
        assert cfg.norm_offset == 1.0 and cfg.embed_scale == 64.0 ** 0.5
        tokens = np.random.default_rng(4).integers(0, 255, size=(2, 9))
        ours = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        theirs = hf_logits(model, tokens)
        np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-3)


class TestStarcoder2Parity:
    """StarCoder2 quirks: LayerNorm (with biases), ungated GeLU MLP
    (c_fc/c_proj), qkv/o biases (reference zoo: starcoder2 3b/7b/15b)."""

    def test_logits_match_hf(self, tmp_path):
        import torch
        from transformers import Starcoder2Config, Starcoder2ForCausalLM

        from reval_tpu.models import load_checkpoint, logits_for_tokens

        torch.manual_seed(3)
        cfg_hf = Starcoder2Config(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512, use_bias=True, sliding_window=None,
            tie_word_embeddings=False,
        )
        model = Starcoder2ForCausalLM(cfg_hf).eval()
        path = tmp_path / "tiny-starcoder2"
        model.save_pretrained(path, safe_serialization=True)
        params, cfg = load_checkpoint(path, dtype="float32")
        assert cfg.family == "starcoder2" and cfg.use_layernorm
        assert not cfg.mlp_gated and cfg.attention_bias
        tokens = np.random.default_rng(5).integers(0, 255, size=(2, 9))
        ours = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        theirs = hf_logits(model, tokens)
        np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-3)


class TestSlidingWindowParity:
    """Sliding-window attention (Mistral/StarCoder2): with window < seq_len
    our logits must match HF's, which masks keys older than the window
    (verdict round-1 item 6: the config flag was parsed but ignored)."""

    def _mistral(self, tmp_path, window):
        import torch
        from transformers import MistralConfig, MistralForCausalLM

        from reval_tpu.models import load_checkpoint

        torch.manual_seed(5)
        cfg_hf = MistralConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512, sliding_window=window,
            attn_implementation="eager",
        )
        model = MistralForCausalLM(cfg_hf).eval()
        path = tmp_path / f"tiny-mistral-swa{window}"
        model.save_pretrained(path, safe_serialization=True)
        params, cfg = load_checkpoint(path, dtype="float32")
        assert cfg.sliding_window == window
        return model, params, cfg

    def test_prefill_logits_match_hf(self, tmp_path):
        from reval_tpu.models import logits_for_tokens

        model, params, cfg = self._mistral(tmp_path, window=8)
        tokens = np.random.default_rng(7).integers(0, 255, size=(2, 24))
        ours = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        theirs = hf_logits(model, tokens)
        np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)

    def test_window_actually_masks(self, tmp_path):
        """Same prompt, window on vs off, seq_len > window → logits differ
        (guards against the flag silently reverting to full attention)."""
        from reval_tpu.models import load_checkpoint, logits_for_tokens

        model, params, cfg = self._mistral(tmp_path, window=8)
        tokens = np.random.default_rng(9).integers(0, 255, size=(1, 24))
        with_window = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))
        import dataclasses

        cfg_full = dataclasses.replace(cfg, sliding_window=None)
        full = np.asarray(logits_for_tokens(params, cfg_full, jnp.asarray(tokens)))
        # early positions (inside the window) identical, late ones differ
        np.testing.assert_allclose(with_window[:, :8], full[:, :8], atol=1e-5)
        assert not np.allclose(with_window[:, -1], full[:, -1], atol=1e-4)

    def test_decode_matches_prefill_with_window(self, tmp_path):
        """Token-by-token decode through the windowed cache must agree with
        the windowed prefill logits at every position."""
        import jax

        from reval_tpu.models import (
            decode_step, init_kv_cache, logits_for_tokens, prefill,
        )

        _, params, cfg = self._mistral(tmp_path, window=8)
        tokens = np.random.default_rng(11).integers(0, 255, size=(1, 20))
        ref = np.asarray(logits_for_tokens(params, cfg, jnp.asarray(tokens)))

        t0 = 4                                    # prefill 4, decode the rest
        cache = init_kv_cache(cfg, 1, 32, dtype=params["embed"].dtype)
        pad = jnp.zeros(1, jnp.int32)
        logits, cache = prefill(params, cfg, jnp.asarray(tokens[:, :t0]), pad, cache)
        got = [np.asarray(logits)[:, -1]]
        for pos in range(t0, tokens.shape[1]):
            step_logits, cache = decode_step(
                params, cfg, jnp.asarray(tokens[:, pos:pos + 1]), pad,
                cache, jnp.int32(pos))
            got.append(np.asarray(step_logits))
        for i, g in enumerate(got[:-1]):          # got[i] predicts pos t0+i
            np.testing.assert_allclose(g, ref[:, t0 - 1 + i], atol=2e-4, rtol=2e-3)
