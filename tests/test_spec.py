"""Greedy n-gram speculative decoding (models/spec.py + paged engine):
the emitted text must be BIT-IDENTICAL to token-by-token greedy decode in
every composition — acceptance only changes speed, never output."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax.numpy as jnp

from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params

PAGE = 128

PROMPTS = [
    "def add(a, b):\n    return a + b\nassert add(",
    "x = 1",
    "for i in range(10):\n    print(i)",
    "y = [k * k for k in range(5)]",
]


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    return cfg, params


def engines(tiny, spec_k=4, **kw):
    cfg, params = tiny
    plain = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512, **kw)
    spec = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                          page_size=PAGE, max_seq_len=512, spec_k=spec_k,
                          **kw)
    return plain, spec


def test_spec_greedy_bit_identical(tiny):
    plain, spec = engines(tiny)
    try:
        want = plain.generate(PROMPTS, max_new_tokens=48, temperature=0.0)
        got = spec.generate(PROMPTS, max_new_tokens=48, temperature=0.0)
        assert got == want
        # random tiny models loop hard, so the bigram draft lands often —
        # prove the speculative path actually ran and accepted something
        assert spec.stats.spec_rounds > 0
        assert spec.stats.spec_accepted > 0, "draft never accepted"
        # the economics: weight passes per emitted token never exceed 1
        # (every verify round emits at least its bonus token)
        assert spec.stats.spec_rounds <= spec.stats.generated_tokens
    finally:
        plain.close()
        spec.close()


def test_spec_respects_budget_exactly(tiny):
    plain, spec = engines(tiny)
    try:
        for budget in (1, 3, 17):
            want = plain.generate([PROMPTS[0]], max_new_tokens=budget,
                                  temperature=0.0)
            got = spec.generate([PROMPTS[0]], max_new_tokens=budget,
                                temperature=0.0)
            assert got == want, budget
    finally:
        plain.close()
        spec.close()


def test_spec_stop_strings(tiny):
    plain, spec = engines(tiny)
    try:
        full = plain.generate([PROMPTS[2]], max_new_tokens=32,
                              temperature=0.0)[0]
        if len(full) < 4:
            pytest.skip("random model produced no usable text")
        stop = full[1:3]
        want = plain.generate([PROMPTS[2]], max_new_tokens=32, stop=[stop],
                              temperature=0.0)
        got = spec.generate([PROMPTS[2]], max_new_tokens=32, stop=[stop],
                            temperature=0.0)
        assert got == want
    finally:
        plain.close()
        spec.close()


def test_spec_slot_reuse_and_order(tiny):
    plain, spec = engines(tiny)
    try:
        want = plain.generate(PROMPTS * 2, max_new_tokens=12, temperature=0.0)
        got = spec.generate(PROMPTS * 2, max_new_tokens=12, temperature=0.0)
        assert got == want
    finally:
        plain.close()
        spec.close()


def test_spec_with_preemption(tiny):
    """Tiny pool: sequences preempt (resume-style) mid-speculation and
    the output still equals uncontended greedy."""
    cfg, params = tiny
    roomy = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512)
    want = roomy.generate(PROMPTS[:3], max_new_tokens=8, temperature=0.0)
    roomy.close()
    tight = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512, num_pages=5,
                           spec_k=4, spec_rounds=2)
    try:
        got = tight.generate(PROMPTS[:3], max_new_tokens=8, temperature=0.0)
        assert got == want
    finally:
        tight.close()


def test_spec_with_prefix_sharing(tiny):
    cfg, params = tiny
    template = "# few shot\n" + "def ex():\n    pass\n" * 20
    prompts = [template + f"\ndef f_{i}(x):" for i in range(4)]
    plain = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=1024)
    spec = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                          page_size=PAGE, max_seq_len=1024, spec_k=4)
    try:
        want = plain.generate(prompts, max_new_tokens=16, temperature=0.0)
        got = spec.generate(prompts, max_new_tokens=16, temperature=0.0)
        assert got == want
    finally:
        plain.close()
        spec.close()


def test_spec_disabled_for_sampled_requests(tiny):
    """temperature>0 requests take the regular keyed-sampling path (spec
    is greedy-only), preserving the per-request stream guarantee."""
    cfg, params = tiny
    a = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                       page_size=PAGE, max_seq_len=512, seed=9)
    b = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                       page_size=PAGE, max_seq_len=512, seed=9, spec_k=4)
    try:
        want = a.generate(PROMPTS[:2], max_new_tokens=16, temperature=0.8)
        got = b.generate(PROMPTS[:2], max_new_tokens=16, temperature=0.8)
        assert got == want
        assert b.stats.spec_rounds == 0
    finally:
        a.close()
        b.close()


def test_spec_with_int8_kv(tiny):
    cfg, params = tiny
    plain = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                           page_size=PAGE, max_seq_len=512, kv_dtype="int8")
    spec = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=2,
                          page_size=PAGE, max_seq_len=512, kv_dtype="int8",
                          spec_k=4)
    try:
        want = plain.generate(PROMPTS[:2], max_new_tokens=16, temperature=0.0)
        got = spec.generate(PROMPTS[:2], max_new_tokens=16, temperature=0.0)
        assert got == want
    finally:
        plain.close()
        spec.close()


def test_draft_ngram_proposes_following_tokens():
    from reval_tpu.models.spec import draft_ngram

    hist = jnp.asarray(np.array([[5, 6, 7, 8, 9, 1, 2, 5, 6, 0, 0, 0]],
                                np.int32))
    # trailing bigram (5, 6) last occurred at 0..1 -> propose 7, 8, 9
    cand = draft_ngram(hist, jnp.asarray([9], jnp.int32), 3)
    assert cand.tolist() == [[7, 8, 9]]
