"""Speculative + constrained decoding (reval_tpu/decoding/ + the paged
engine's batched verify path).

The load-bearing assertions:

- **grammar bite** — per-task answer shapes compile to token automata
  under which an out-of-grammar token is IMPOSSIBLE (every raw generated
  id walks the mask), for greedy and sampled rows alike;
- **the greedy-accept contract** — speculation on/off is bit-identical
  over REval-shaped probes (raw id streams, not text), with ≥2× fewer
  engine decode steps on grammar-constrained coverage-shaped prompts;
- **exact page bookkeeping** — rejected drafts roll the runtime length
  back (pages free; no drift toward max_pages_per_seq), and the contract
  survives preemption on a tiny pool × a warm prefix cache;
- **spec.wedge degrade** — a faulting drafter downgrades ONLY its
  request to plain decode, mid-request, bit-identically;
- **dp work-stealing parity** and the serving path (session submit +
  HTTP ``grammar=`` end-to-end over the mock engine, unknown names 400).
"""

import json

import numpy as np
import pytest

from reval_tpu.decoding import (GrammarSet, NgramIndex, TASK_GRAMMARS,
                                propose, validate_grammar)
from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params

PAGE = 128


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,  # 320
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    return cfg, params


def mk_engine(tiny, *, spec=None, slots=4, max_seq=256, pages=None,
              prefix=True, page=PAGE):
    cfg, params = tiny
    return PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=slots,
                          page_size=page, max_seq_len=max_seq,
                          num_pages=pages, prefix_sharing=prefix,
                          speculative=spec)


PROBES = [
    "Is line 2 executed when f(3) is called?\n[ANSWER]",
    "def add(a, b):\n    return a + b\nIs line 2 executed?\n[ANSWER]",
    "x = 1\nwhile x < 9:\n    x *= 2\nWhat is x?\n[ANSWER]",
]


# -- grammar compilation bites --------------------------------------------
class TestGrammar:
    def _walk_legal(self, gs, start, ids):
        state = start
        for t in ids:
            assert gs.allowed(state, t), \
                f"token {t!r} ({chr(t) if t < 256 else t}) emitted in " \
                f"state {state} where the mask forbids it"
            state = int(gs.next[state, t])
        return state

    def test_per_task_shapes_compile_and_accept_canonical_answers(self):
        gs = GrammarSet(ByteTokenizer(), 320)
        canonical = {"coverage": "NO", "path": "    return x*2",
                     "state": "4; int", "output":
                     "assertEqual(a.f(4), 7)"}
        for task, shape in TASK_GRAMMARS.items():
            start = gs.start_state(shape)
            text = "\n" + canonical[task] + "\n[/ANSWER]"
            end = self._walk_legal(gs, start, [ord(c) for c in text])
            assert end == 0, f"{shape}: canonical answer did not close"
            # the cot variant accepts the same answer after free thought
            cstart = gs.start_state(f"cot-{shape}")
            cot = "because...\n[/THOUGHT]\n[ANSWER]" + text
            assert gs.walk(cstart, [ord(c) for c in cot]) == 0

    def test_yesno_forbids_everything_but_the_alternatives(self):
        gs = GrammarSet(ByteTokenizer(), 320)
        s = gs.start_state("yesno")
        allowed = {t for t in range(320) if gs.allowed(s, t)}
        assert allowed == {ord("\n"), ord("Y"), ord("N")}
        # mid-literal: after 'YE' exactly one continuation, and it is
        # what the drafter force-proposes
        st = gs.walk(s, [ord("Y"), ord("E")])
        assert int(gs.forced[st]) == ord("S")
        # EOS is impossible mid-answer, legal once the tag closed (FREE)
        assert not gs.allowed(st, ByteTokenizer().eos_id)
        done = gs.walk(s, [ord(c) for c in "YES\n[/ANSWER]"])
        assert done == 0 and gs.allowed(done, ByteTokenizer().eos_id)

    def test_state_shape_requires_semicolon_before_close(self):
        gs = GrammarSet(ByteTokenizer(), 320)
        s = gs.start_state("state")
        mid = gs.walk(s, [ord(c) for c in "42"])
        assert not gs.allowed(mid, ord("\n"))   # no close without a ';'
        assert gs.walk(mid, [ord(c) for c in "; int\n[/ANSWER]"]) == 0
        assert gs.walk(s, [ord(c) for c in "Nil\n[/ANSWER]"]) == 0

    def test_unknown_grammar_rejected_everywhere(self, tiny):
        with pytest.raises(ValueError):
            validate_grammar("bogus-shape")
        eng = mk_engine(tiny)
        try:
            with pytest.raises(ValueError):
                eng.generate(["x"], max_new_tokens=4, grammar="bogus-shape")
            with pytest.raises(ValueError):
                eng.submit_request([1, 2, 3], 4, grammar="bogus-shape")
        finally:
            eng.close()
        from reval_tpu.serving.server import _validate_request
        with pytest.raises(ValueError):
            _validate_request({"prompt": "x", "grammar": "bogus"}, None)
        assert _validate_request({"prompt": "x", "grammar": "yesno"},
                                 None)["grammar"] == "yesno"

    def test_out_of_grammar_token_impossible_in_generation(self, tiny):
        """The tentpole bite: walk every RAW generated id through the
        mask — at no point may the engine have emitted a token the
        automaton forbids (greedy AND sampled, spec on AND off)."""
        for spec in (False, None):
            eng = mk_engine(tiny, spec=spec)
            gs = eng._grammars
            try:
                for temp in (0.0, 0.9):
                    _, ids = eng.generate(
                        PROBES, max_new_tokens=20, temperature=temp,
                        grammar="yesno", return_ids=True)
                    for row in ids:
                        TestGrammar()._walk_legal(
                            gs, gs.start_state("yesno"), row)
            finally:
                eng.close()

    def test_static_engine_rejects_grammar_loudly(self, tiny):
        from reval_tpu.inference.tpu.engine import TPUEngine

        cfg, params = tiny
        eng = TPUEngine(params, cfg, ByteTokenizer(), batch_size=2,
                        max_seq_len=256)
        with pytest.raises(ValueError, match="paged"):
            eng.generate(["x"], max_new_tokens=4, grammar="yesno")


# -- drafting --------------------------------------------------------------
class TestDraft:
    def test_ngram_index_never_matches_its_own_tail(self):
        idx = NgramIndex(3, [1, 2, 3, 4])
        assert idx.match([2, 3, 4]) is None     # the tail IS the stream end
        idx.extend([1, 2, 3, 9])
        # stream [1,2,3,4,1,2,3,9]: the LATEST completed occurrence of
        # (1,2,3) ends before index 7 — recency wins, continuation 9
        assert idx.match([1, 2, 3]) == 7
        drafts, forced = propose(idx, 4)
        # the tail itself is (2,3,9): no completed earlier occurrence
        assert drafts == [] and forced == 0
        idx2 = NgramIndex(2, [5, 6, 7, 5, 6])
        drafts2, _ = propose(idx2, 4)
        assert drafts2[:1] == [7]               # (5,6) continues with 7

    def test_grammar_forced_chain_is_free(self):
        gs = GrammarSet(ByteTokenizer(), 320)
        st = gs.walk(gs.start_state("yesno"), [ord("N")])
        drafts, forced = propose(None, 16, gs, st)
        assert "".join(chr(t) for t in drafts) == "O\n[/ANSWER]"
        assert forced == len(drafts)

    def test_span_stops_at_out_of_grammar_token(self):
        gs = GrammarSet(ByteTokenizer(), 320)
        # history continues "YX" after the tail; 'X' is out of grammar
        idx = NgramIndex(2, [ord(c) for c in "abYXab"])
        st = gs.start_state("yesno")        # allows only \n, Y, N
        drafts, _ = propose(idx, 8, gs, st)
        assert ord("X") not in drafts


# -- the greedy-accept contract -------------------------------------------
class TestAcceptContract:
    def test_spec_on_off_bit_identical_and_2x_fewer_steps(self, tiny):
        """The acceptance criterion: byte-identical greedy outputs with
        ≥2× fewer engine decode steps on coverage-shaped constrained
        probes, accept-rate surfaced in the counters."""
        runs = {}
        for name, spec in (("off", False), ("on", None)):
            eng = mk_engine(tiny, spec=spec)
            try:
                out, _ = eng.generate(
                    PROBES, max_new_tokens=24, temperature=0.0,
                    stop=["[/ANSWER]"], grammar="yesno", return_ids=True)
                # raw streams compare WITHOUT a stop string: post-stop
                # chunk overrun differs by chunking schedule by design
                # (finalize cuts it), so the raw contract is budget-run
                _, ids = eng.generate(
                    PROBES, max_new_tokens=16, temperature=0.0,
                    grammar="yesno", return_ids=True)
                runs[name] = (out, ids, eng.stats.decode_steps,
                              eng.spec_counters())
            finally:
                eng.close()
        assert runs["on"][0] == runs["off"][0]
        assert runs["on"][1] == runs["off"][1]
        steps_off, steps_on = runs["off"][2], runs["on"][2]
        assert steps_on * 2 <= steps_off, (steps_on, steps_off)
        sc = runs["on"][3]
        assert sc["rounds"] > 0 and sc["accepted_tokens"] > 0
        assert 0 < sc["accept_rate"] <= 1.0
        assert sc["forced_tokens"] > 0          # grammar forcing engaged
        off = runs["off"][3]
        assert off["rounds"] == 0 and off["drafted_tokens"] == 0

    def test_ngram_only_speculation_bit_identical(self, tiny):
        """speculative=True drafts grammar-less greedy rows from their
        own context (prompt lookup) — same stream as plain decode."""
        prompts = ["def f(a, b):\n    return a + b\ndef g(a, b):\n    ret",
                   "x = 1\nwhile x < 9:\n    x *= 2\nwhile x < 9:\n"]
        base_eng = mk_engine(tiny, spec=False)
        base = base_eng.generate(prompts, max_new_tokens=16,
                                 temperature=0.0, return_ids=True)
        base_eng.close()
        eng = mk_engine(tiny, spec=True)
        try:
            got = eng.generate(prompts, max_new_tokens=16,
                               temperature=0.0, return_ids=True)
            assert got == base
            assert eng.stats.spec_rounds > 0
        finally:
            eng.close()

    def test_kill_switch_env(self, tiny, monkeypatch):
        monkeypatch.setenv("REVAL_TPU_SPEC", "0")
        eng = mk_engine(tiny)       # speculative=None reads the env
        try:
            out = eng.generate(PROBES[:1], max_new_tokens=12,
                               temperature=0.0, grammar="yesno")
            assert eng.stats.spec_rounds == 0
            assert eng.stats.grammar_requests == 1   # masking still on
            assert "YES" in out[0] or "NO" in out[0]
        finally:
            eng.close()

    def test_mixed_grammar_batch_masks_only_named_rows(self, tiny):
        """Per-prompt grammar lists (the fleet's fused shape): the named
        row obeys its shape, the unconstrained row decodes exactly as a
        grammar-less run would."""
        eng = mk_engine(tiny)
        try:
            out, ids = eng.generate(
                PROBES[:2], max_new_tokens=12, temperature=0.0,
                grammar=["yesno", None], return_ids=True)
        finally:
            eng.close()
        base_eng = mk_engine(tiny, spec=False)
        try:
            _, base_ids = base_eng.generate(
                PROBES[:2], max_new_tokens=12, temperature=0.0,
                return_ids=True)
        finally:
            base_eng.close()
        assert ids[1] == base_ids[1]            # unconstrained row untouched
        gs = GrammarSet(ByteTokenizer(), 320)
        TestGrammar()._walk_legal(gs, gs.start_state("yesno"), ids[0])


# -- page bookkeeping ------------------------------------------------------
class TestPageBookkeeping:
    def test_runtime_rollback_frees_rejected_tail_pages(self):
        from reval_tpu.runtime import PagedRuntime

        rt = PagedRuntime(num_pages=16, page_size=8, max_slots=2,
                          max_pages_per_seq=8)
        sid = rt.submit(10, 30)
        assert rt.admit()
        free0 = rt.free_pages
        assert rt.advance(sid, 9) == 19         # window reserve: +1 page
        assert rt.free_pages == free0 - 1
        rt.rollback(sid, 11)                    # 8 of 9 rejected
        assert rt.seq_len(sid) == 11 and rt.free_pages == free0
        with pytest.raises(ValueError):
            rt.rollback(sid, 9)                 # below prompt_len
        with pytest.raises(ValueError):
            rt.rollback(sid, 12)                # above len
        # prefix pages are never rolled away
        pid = rt.alloc_prefix(2)
        rid = rt.submit_prefixed(pid, 17, 8)
        rt.admit()
        rt.advance(rid, 4)
        rt.rollback(rid, 17)
        assert rt.prefix_pages(rid) == 2
        rt.release(rid)
        rt.release(pid)
        rt.release(sid)
        rt.close()

    def test_no_length_drift_across_many_rounds(self, tiny):
        """Rejected drafts must not inflate the runtime length round
        over round (un-rolled-back reservations would creep toward
        max_pages_per_seq and spuriously OOM/preempt)."""
        eng = mk_engine(tiny, spec=None, max_seq=512)
        try:
            out = eng.generate(
                PROBES, max_new_tokens=48, temperature=0.0,
                grammar="line", return_ids=True)[1]
            sc = eng.spec_counters()
            assert sc["rounds"] >= 2
            # every sequence released; all non-cache pages back
            assert eng.rt.num_running == 0 and eng.rt.num_waiting == 0
            cached = (eng.prefix_cache.cached_pages
                      if eng.prefix_cache else 0)
            assert eng.rt.free_pages == eng.num_pages - 1 - cached
            assert all(len(r) <= 48 for r in out)
        finally:
            eng.close()

    def test_preemption_x_prefix_cache_bit_identical(self, tiny):
        """The hard satellite: a pool too small for the batch (forced
        preemption) plus a warm radix prefix cache, speculating — the
        streams still match the unconstrained-resources plain run.
        Small pages (16) so the verify windows straddle page boundaries
        and the shared template spans many cached pages."""
        shared = ("You are given a Python function and a question. "
                  "Answer with YES or NO only. " * 2)
        prompts = [shared + p for p in PROBES]
        big = mk_engine(tiny, spec=False, slots=2, max_seq=512, page=16)
        try:
            big.generate(prompts, max_new_tokens=40, temperature=0.0,
                         grammar="yesno")     # warm its cache like below
            want = big.generate(prompts, max_new_tokens=40,
                                temperature=0.0, grammar="yesno",
                                return_ids=True)
        finally:
            big.close()
        # template ~10 cached pages + 2 riders' tails + decode growth on
        # a tight pool: advance() must hit OOM mid-run and preempt
        small = mk_engine(tiny, spec=None, slots=2, max_seq=512, page=16,
                          pages=24)
        preempts = []
        orig = small.rt.preempt
        small.rt.preempt = lambda s, n: (preempts.append(s), orig(s, n))[1]
        try:
            small.generate(prompts, max_new_tokens=40, temperature=0.0,
                           grammar="yesno")
            got = small.generate(prompts, max_new_tokens=40,
                                 temperature=0.0, grammar="yesno",
                                 return_ids=True)
            sc = small.spec_counters()
        finally:
            small.close()
        assert got == want
        assert sc["rounds"] > 0
        assert preempts, "pool was large enough — shrink pages to keep " \
                         "this test biting"


# -- spec.wedge degrade ----------------------------------------------------
class TestWedge:
    def test_drafter_fault_degrades_mid_request(self, tiny, monkeypatch):
        calls = {"n": 0}
        import reval_tpu.inference.tpu.paged_engine as pe

        real = pe.propose_drafts

        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] > 2:
                raise RuntimeError("drafter exploded")
            return real(*a, **k)

        base_eng = mk_engine(tiny, spec=False)
        want = base_eng.generate(PROBES, max_new_tokens=24,
                                 temperature=0.0, grammar="yesno",
                                 return_ids=True)
        base_eng.close()
        monkeypatch.setattr(pe, "propose_drafts", flaky)
        eng = mk_engine(tiny, spec=None)
        try:
            got = eng.generate(PROBES, max_new_tokens=24, temperature=0.0,
                               grammar="yesno", return_ids=True)
            sc = eng.spec_counters()
        finally:
            eng.close()
        assert got == want                       # bit-identical through it
        assert sc["wedges"] >= 1                 # rows degraded, counted
        assert sc["rounds"] >= 1                 # speculation DID start

    def test_wedge_event_logged(self, tiny, monkeypatch):
        import reval_tpu.inference.tpu.paged_engine as pe
        from reval_tpu.obs.logging import recent

        monkeypatch.setattr(pe, "propose_drafts",
                            lambda *a, **k: (_ for _ in ()).throw(
                                RuntimeError("boom")))
        eng = mk_engine(tiny, spec=None)
        try:
            eng.generate(PROBES[:1], max_new_tokens=8, temperature=0.0,
                         grammar="yesno")
        finally:
            eng.close()
        assert any(e.get("event") == "spec.wedge" for e in recent(64))


# -- dp work-stealing parity ----------------------------------------------
class TestDpParity:
    def test_dp2_matches_single_engine_with_grammar_and_spec(self, tiny):
        from reval_tpu.inference.tpu.dp_paged import DataParallelPagedEngine

        cfg, params = tiny
        single = mk_engine(tiny, spec=None, slots=2)
        try:
            want = single.generate(PROBES * 2, max_new_tokens=12,
                                   temperature=0.0, grammar="yesno",
                                   return_ids=True)
        finally:
            single.close()
        dp = DataParallelPagedEngine(params, cfg, ByteTokenizer(),
                                     dp_size=2, max_slots=2, page_size=PAGE,
                                     max_seq_len=256, speculative=None)
        try:
            got = dp.generate(PROBES * 2, max_new_tokens=12,
                              temperature=0.0, grammar="yesno",
                              return_ids=True)
            sc = dp.spec_counters()
        finally:
            dp.close()
        assert got == want
        assert sc["grammar_requests"] == len(PROBES) * 2
        assert sc["rounds"] > 0


# -- serving path ----------------------------------------------------------
class TestServing:
    def test_session_submit_grammar_over_paged_engine(self, tiny):
        from reval_tpu.serving.session import ContinuousSession

        base_eng = mk_engine(tiny, spec=False)
        want = base_eng.generate(PROBES, max_new_tokens=16,
                                 temperature=0.0, grammar="yesno")
        base_eng.close()
        eng = mk_engine(tiny, spec=None)
        session = ContinuousSession(eng, watchdog_s=0)
        try:
            got = session.submit(PROBES, max_new_tokens=16,
                                 grammar="yesno").result(timeout=120)
            with pytest.raises(ValueError):
                session.submit(["x"], max_new_tokens=4, grammar="nope")
        finally:
            session.close()
            eng.close()
        assert got == want
        assert eng.stats.grammar_requests == len(PROBES)

    def test_serve_mock_grammar_end_to_end(self):
        """The serve --mock smoke shape: HTTP grammar= flows through the
        session into the mock engine (counted), unknown names 400."""
        import urllib.error
        import urllib.request

        from reval_tpu.serving.mock_engine import MockStepEngine
        from reval_tpu.serving.server import EngineServer
        from reval_tpu.serving.session import ContinuousSession

        eng = MockStepEngine()
        session = ContinuousSession(eng, watchdog_s=0)
        server = EngineServer(session.generate_fn(), "mock", port=0,
                              serialize=False,
                              ready_fn=session.readiness)
        server.start()
        url = f"http://127.0.0.1:{server.port}/v1/completions"

        def post(body):
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())

        try:
            status, doc = post({"prompt": "hello", "max_tokens": 16,
                                "grammar": "yesno"})
            assert status == 200
            assert doc["choices"][0]["text"]
            assert eng.stats.grammar_requests == 1
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                post({"prompt": "hello", "max_tokens": 16,
                      "grammar": "not-a-shape"})
            assert exc_info.value.code == 400
            body = json.loads(exc_info.value.read())
            assert body["error"]["code"] == "invalid_request"
        finally:
            server.shutdown()
            session.close()


# -- reporting -------------------------------------------------------------
class TestReporting:
    def test_obs_report_speculative_across_rounds(self, tmp_path, capsys):
        import tools.obs_report as obs_report

        rounds = []
        for i, rate in enumerate((0.4, 0.75)):
            p = tmp_path / f"BENCH_r0{i + 1}.json"
            p.write_text(json.dumps({"speculative": {
                "accept_rate": rate, "drafted_tokens": 100,
                "accepted_tokens": int(rate * 100),
                "steps_saved_ratio": 1.0 + rate, "wedges": 0}}))
            rounds.append(str(p))
        noblock = tmp_path / "BENCH_r00.json"
        noblock.write_text(json.dumps({"metric": "x"}))
        rc = obs_report.main(["--speculative", str(noblock)] + rounds)
        out = capsys.readouterr().out
        assert rc == 0
        assert "no speculative block" in out
        assert "+0.350" in out                   # the round-over-round delta

    def test_fleet_grammar_selection_map(self):
        from reval_tpu.fleet import FleetRunner

        fr = FleetRunner(dataset="humaneval", mock=True, grammar=True,
                         progress=False)
        assert fr.task_grammar("coverage") == "yesno"
        assert fr.task_grammar("output") == "assert"
        assert fr.task_grammar("unknown-task") is None
        cot = FleetRunner(dataset="humaneval", mock=True, grammar=True,
                          prompt_type="cot", progress=False)
        assert cot.task_grammar("path") == "cot-line"
        off = FleetRunner(dataset="humaneval", mock=True, progress=False)
        assert off.task_grammar("coverage") is None

    def test_fleet_rejects_grammar_without_capable_backend(self):
        from reval_tpu.fleet import FleetRunner

        class Dumb:
            info = "dumb_direct_temp0.0"

        with pytest.raises(ValueError, match="grammar"):
            FleetRunner(dataset="humaneval", backend=Dumb(), grammar=True,
                        progress=False, resilience=False)

    def test_spec_counters_shape_everywhere(self, tiny):
        from reval_tpu.serving.mock_engine import MockStepEngine

        eng = mk_engine(tiny)
        mock = MockStepEngine()
        try:
            keys = set(eng.spec_counters())
            assert keys == set(mock.spec_counters())
            assert {"rounds", "accept_rate", "drafted_tokens",
                    "accepted_tokens", "rolled_back_tokens",
                    "wedges"} <= keys
        finally:
            eng.close()
