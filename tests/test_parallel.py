"""Sharding tests on the virtual 8-device CPU mesh (SURVEY §4: multi-chip
TP/DP must be testable without a pod — assert shardings + numerical parity
vs single-device)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # noqa: E402

import jax
import jax.numpy as jnp

from reval_tpu.inference.tpu.engine import TPUEngine
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.models import ModelConfig, init_random_params
from reval_tpu.parallel import make_mesh, mesh_axis_sizes, param_specs, shard_params


def tiny_cfg(**overrides):
    base = dict(
        vocab_size=ByteTokenizer.vocab_size, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
    )
    return ModelConfig(**{**base, **overrides})


class TestMesh:
    def test_eight_cpu_devices(self):
        assert len(jax.devices()) == 8

    def test_make_mesh_axes(self):
        mesh = make_mesh(tp=2, dp=2, sp=2)
        assert mesh_axis_sizes(mesh) == {"dp": 2, "pp": 1, "sp": 2,
                                         "ep": 1, "tp": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="needs 16 devices"):
            make_mesh(tp=4, dp=4)


class TestParamSharding:
    def test_specs_cover_all_leaves(self):
        cfg = tiny_cfg()
        params = init_random_params(cfg, dtype="float32")
        mesh = make_mesh(tp=2, dp=2)
        specs = param_specs(params, cfg, mesh)
        flat_p = jax.tree_util.tree_leaves(params)
        flat_s = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: not isinstance(x, dict))
        assert len(flat_p) == len(flat_s)

    def test_tp_sharded_leaves(self):
        cfg = tiny_cfg()
        params = init_random_params(cfg, dtype="float32")
        mesh = make_mesh(tp=2, dp=2)
        sharded = shard_params(params, cfg, mesh)
        q_spec = sharded["layers"]["q_w"].sharding.spec
        assert q_spec == jax.sharding.PartitionSpec(None, None, "tp")
        o_spec = sharded["layers"]["o_w"].sharding.spec
        assert o_spec == jax.sharding.PartitionSpec(None, "tp", None)
        # norms replicated
        assert sharded["layers"]["attn_norm_w"].sharding.spec == jax.sharding.PartitionSpec()

    def test_indivisible_falls_back_to_replication(self):
        cfg = tiny_cfg(num_kv_heads=3, num_heads=3, intermediate_size=126, vocab_size=255)
        params = init_random_params(cfg, dtype="float32")
        mesh = make_mesh(tp=2)
        specs = param_specs(params, cfg, mesh)
        assert specs["layers"]["k_w"] == jax.sharding.PartitionSpec()
        assert specs["embed"] == jax.sharding.PartitionSpec()


class TestShardedGenerationParity:
    """The crown test: tp×dp generation must reproduce single-device greedy
    output exactly (same tokens)."""

    @pytest.fixture(scope="class")
    def setup(self):
        cfg = tiny_cfg()
        params = init_random_params(cfg, seed=3, dtype="float32")
        single = TPUEngine(params, cfg, ByteTokenizer(), batch_size=4, max_seq_len=512)
        return cfg, params, single

    @pytest.mark.parametrize("tp,dp", [(2, 1), (1, 2), (2, 2), (4, 2)])
    def test_parity(self, setup, tp, dp):
        cfg, params, single = setup
        mesh = make_mesh(tp=tp, dp=dp)
        sharded = TPUEngine(params, cfg, ByteTokenizer(), batch_size=4,
                            max_seq_len=512, mesh=mesh)
        prompts = ["hello world", "shard me", "a" * 70]
        base = single.generate(prompts, max_new_tokens=8)
        multi = sharded.generate(prompts, max_new_tokens=8)
        assert base == multi

    def test_paged_parity_gqa_heads_divisible_kv_not(self):
        """tp divides the query heads but NOT the kv heads (h=4, h_kv=2,
        tp=4): the tp-manual attention wrapper must fall back to
        replicated q — a head-sharded q against replicated kv silently
        pairs query heads with the wrong kv groups (advisor round-5)."""
        from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine

        cfg = tiny_cfg()              # h=4, h_kv=2; tp=4 → kv indivisible
        params = init_random_params(cfg, seed=5, dtype="float32")
        prompts = ["def f(x):", "assert f(", "b" * 60]
        single = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=3,
                                page_size=64, max_seq_len=256)
        base = single.generate(prompts, max_new_tokens=8)
        single.close()
        sharded = PagedTPUEngine(params, cfg, ByteTokenizer(), max_slots=3,
                                 page_size=64, max_seq_len=256,
                                 mesh=make_mesh(tp=4))
        multi = sharded.generate(prompts, max_new_tokens=8)
        sharded.close()
        assert base == multi
