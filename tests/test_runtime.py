"""Native paged-KV runtime: allocator, scheduler, fork/preempt semantics.

These exercise the C++ library through the ctypes bindings — the first
test run also proves the build-on-import path works in this image.
"""

import pytest

from reval_tpu.runtime import PagedRuntime

PAGE = 16


@pytest.fixture
def rt():
    r = PagedRuntime(num_pages=9, page_size=PAGE, max_slots=2,
                     max_pages_per_seq=4)
    yield r
    r.close()


def test_trash_page_never_allocated(rt):
    assert rt.free_pages == 8          # page 0 reserved
    ids = [rt.submit(PAGE, 0) for _ in range(2)]
    rt.admit()
    for i in ids:
        assert 0 not in set(rt.block_table(i)[:1])


def test_fcfs_admission_and_tables(rt):
    a = rt.submit(prompt_len=20, max_new_tokens=10)   # 2 pages
    b = rt.submit(prompt_len=5, max_new_tokens=10)    # 1 page
    admitted = rt.admit()
    assert [s for s, _ in admitted] == [a, b]
    assert {slot for _, slot in admitted} == {0, 1}
    assert rt.seq_len(a) == 20 and rt.seq_len(b) == 5
    ta, tb = rt.block_table(a), rt.block_table(b)
    live_a, live_b = set(ta[:2]), {tb[0]}
    assert live_a.isdisjoint(live_b)
    assert list(ta[2:]) == [0, 0] and list(tb[1:]) == [0, 0, 0]


def test_admission_respects_slots_and_watermark(rt):
    first = [rt.submit(PAGE, 0), rt.submit(PAGE, 0), rt.submit(PAGE, 0)]
    admitted = rt.admit()
    assert len(admitted) == 2          # only 2 slots
    assert rt.num_waiting == 1
    # release one; third now fits
    rt.release(first[0])
    assert [s for s, _ in rt.admit()] == [first[2]]
    # huge prompt cannot be admitted while pool lacks pages + watermark
    big = rt.submit(4 * PAGE, 0)       # 4 pages, but only 9-1-2 free...
    assert rt.admit() == [] or rt.seq_len(big) == 4 * PAGE


def test_advance_allocates_on_page_boundary(rt):
    a = rt.submit(PAGE - 1, 10)
    rt.admit()
    assert int((rt.block_table(a) != 0).sum()) == 1
    assert rt.advance(a, 1) == PAGE    # fills the page exactly
    assert int((rt.block_table(a) != 0).sum()) == 1
    assert rt.advance(a, 1) == PAGE + 1  # crosses: new page
    assert int((rt.block_table(a) != 0).sum()) == 2


def test_oom_advance_then_preempt_recovers():
    rt = PagedRuntime(num_pages=4, page_size=PAGE, max_slots=2,
                      max_pages_per_seq=3)
    a = rt.submit(PAGE, PAGE)          # 1 page now, will grow
    b = rt.submit(PAGE, PAGE)
    assert len(rt.admit()) == 2        # 2 pages used, 1 free (watermark)
    assert rt.advance(a, PAGE) == 2 * PAGE   # takes the last free page
    assert rt.advance(b, PAGE) is None       # OOM
    victim = rt.preempt_last()
    assert victim == b                 # youngest running evicted
    assert rt.slot_of(b) == -1 and rt.num_waiting == 1
    # only 1 page free: the watermark (prompt pages + 1) blocks re-admission
    assert rt.admit() == []
    rt.release(a)                      # a finishes → pool drains
    # b re-admits from the queue FRONT; resume semantics folded everything
    # materialised plus the pending sampled token into its prompt, so the
    # re-prefill replays PAGE+1 tokens instead of restarting from PAGE
    assert [s for s, _ in rt.admit()] == [b]
    assert rt.seq_len(b) == PAGE + 1
    rt.close()


def test_release_refcounts_and_reuse(rt):
    a = rt.submit(3 * PAGE, 0)
    rt.admit()
    used = [p for p in rt.block_table(a) if p != 0]
    before = rt.free_pages
    rt.release(a)
    assert rt.free_pages == before + len(used)
    with pytest.raises(KeyError):
        rt.seq_len(a)


def test_fork_shares_full_pages_and_copies_tail(rt):
    a = rt.submit(PAGE + 4, 0)         # 1 full page + partial tail
    rt.admit()
    table_a = [p for p in rt.block_table(a) if p != 0]
    child, fresh = rt.fork(a)
    assert fresh != 0                  # partial tail -> fresh page to copy
    table_c = [p for p in rt.block_table(child) if p != 0]
    assert table_c[0] == table_a[0]    # full page shared
    assert table_c[1] == fresh and fresh != table_a[1]
    assert rt.page_ref(table_a[0]) == 2
    assert rt.seq_len(child) == PAGE + 4
    # shared page survives parent release, freed after child release
    rt.release(a)
    assert rt.page_ref(table_a[0]) == 1
    rt.release(child)
    assert rt.page_ref(table_a[0]) == 0


def test_fork_child_admits_with_inherited_pages(rt):
    """Admission of a fork child must keep its shared pages and inherited
    length — not re-allocate prompt pages on top (review finding)."""
    a = rt.submit(PAGE + 4, 2 * PAGE)
    rt.admit()
    rt.advance(a, PAGE - 4)            # a now holds 2 pages, len = 2*PAGE
    child, fresh = rt.fork(a)
    table_before = list(rt.block_table(child))
    free_before = rt.free_pages
    assert [s for s, _ in rt.admit()] == [child]
    assert list(rt.block_table(child)) == table_before   # nothing re-allocated
    assert rt.free_pages == free_before
    assert rt.seq_len(child) == 2 * PAGE                 # inherited, not reset
    assert rt.advance(child, 1) == 2 * PAGE + 1          # grows into page 3


def test_fork_aligned_length_shares_everything(rt):
    a = rt.submit(2 * PAGE, 0)
    rt.admit()
    child, fresh = rt.fork(a)
    assert fresh == 0                  # nothing to copy
    assert list(rt.block_table(child)) == list(rt.block_table(a))


def test_submit_rejects_impossible_request(rt):
    with pytest.raises(ValueError):
        rt.submit(prompt_len=4 * PAGE, max_new_tokens=1)  # needs 5 pages


def test_whole_pool_prompt_admits_without_watermark():
    """A request whose budget fits its prompt pages may take the last free
    page — the decode watermark must not deadlock it (review finding)."""
    rt = PagedRuntime(num_pages=5, page_size=PAGE, max_slots=1,
                      max_pages_per_seq=4)
    a = rt.submit(prompt_len=4 * PAGE - 8, max_new_tokens=8)  # 4 pages total
    assert [s for s, _ in rt.admit()] == [a]
    assert rt.advance(a, 8) == 4 * PAGE  # grows inside the last page
    rt.release(a)
    # a growing request (needs a 2nd page for decode) still honors the
    # watermark: 4-page prompt + growth cannot admit on a 4-page pool
    with pytest.raises(ValueError):
        rt.submit(prompt_len=4 * PAGE, max_new_tokens=8)
    rt.close()


def test_failed_advance_keeps_length_honest():
    """OOM advance must not round the length up to page capacity
    (review finding: inflated lengths compound across preemptions)."""
    rt = PagedRuntime(num_pages=3, page_size=PAGE, max_slots=2,
                      max_pages_per_seq=2)
    a = rt.submit(prompt_len=PAGE - 2, max_new_tokens=PAGE)
    assert len(rt.admit()) == 1
    b = rt.submit(prompt_len=PAGE, max_new_tokens=0)  # no growth: takes last page
    assert [s for s, _ in rt.admit()] == [b]
    assert rt.free_pages == 0
    before = rt.seq_len(a)
    assert rt.advance(a, PAGE) is None   # needs a 2nd page: OOM
    assert rt.seq_len(a) == before       # unchanged, not snapped to PAGE
    rt.release(b)
    assert rt.advance(a, PAGE) == before + PAGE
    rt.close()


def test_preempt_ignores_unexecuted_reservation():
    """advance() reserves chunk pages BEFORE the decode runs; preempting a
    victim mid-reservation must fold only the tokens the caller reports as
    materialised — not the phantom reserved steps (review finding: the
    drift compounds per preemption and can deadlock a feasible workload)."""
    rt = PagedRuntime(num_pages=8, page_size=PAGE, max_slots=2,
                      max_pages_per_seq=4)
    a = rt.submit(PAGE, 2 * PAGE)
    assert len(rt.admit()) == 1
    # engine view: prefill done, one pending token → materialized == PAGE
    assert rt.advance(a, 8) == PAGE + 8      # chunk reserved, never executed
    rt.preempt(a, PAGE)                      # caller's true count
    assert [s for s, _ in rt.admit()] == [a]
    assert rt.seq_len(a) == PAGE + 1         # not PAGE + 9
    rt.close()


def test_preempt_validates_range():
    rt = PagedRuntime(num_pages=8, page_size=PAGE, max_slots=2,
                      max_pages_per_seq=4)
    a = rt.submit(PAGE, PAGE)
    with pytest.raises(ValueError):
        rt.preempt(a, PAGE)                  # waiting, not running
    rt.admit()
    with pytest.raises(ValueError):
        rt.preempt(a, PAGE + 5)              # beyond runtime len
    with pytest.raises(ValueError):
        rt.preempt(a, PAGE - 2)              # below prompt_len - 1
    rt.preempt(a, PAGE)
    rt.close()
