"""The measurement tools must never burn a chip window on a tool bug.

Every artifact in tools/chip_runbook.sh is produced by bench.py or a
tools/ script; the TPU tunnel is up for ~minutes between multi-hour
wedges (PERF.md), so a crash found on-chip costs a window.  Each tool
has a ``--tiny`` CPU mode — run it as a real subprocess (the runbook's
invocation shape) and assert it exits 0 with the expected markers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_tool(*argv: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, *argv], cwd=REPO,
                          capture_output=True, text=True, timeout=1200)


def test_bench_tiny_emits_one_json_line():
    r = run_tool("bench.py", "--tiny")
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE json line, got {lines}"
    d = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(d)
    assert "error" not in d
    assert d["value"] > 0
    # round-4 verdict item 5: every successful artifact answers "actually
    # fast?" via the HBM roofline lens, not just MFU
    assert d["decode_steps"] > 0
    assert d["hbm_gbps_achieved"] > 0
    assert 0 < d["bandwidth_util"] < 1
    # persistent prefix cache counters + the cache-off A/B row
    pc = d["prefix_cache"]
    assert {"hit_tokens", "hit_rate", "evictions", "pinned_pages",
            "warm_prefill_reduction"} <= set(pc)
    assert pc["warm_prefill_reduction"] > 0
    assert "no_prefix_cache_speedup" in d
    # warm-restart block: ALWAYS present ({"enabled": false} without
    # REVAL_TPU_AOT_CACHE_DIR), so the BENCH_r* trajectory shows exactly
    # when the cold-start win lands
    assert "enabled" in d["restart"]
    if d["restart"]["enabled"]:
        assert "restart_to_ready_s" in d["restart"]
    # the determinism block: reference-cell greedy fingerprint recorded
    # every round so BENCH history detects silent cross-commit drift
    det = d["determinism"]
    assert det["reference"] == "paged-xla-fp32-b2"
    assert len(det["fingerprint"]) == 16
    assert det["cells_run"] >= 3
    assert det["gate_failures"] == []


def test_bench_failure_carries_last_known():
    """Round-4 verdict item 2: a wedged round must record the newest
    clean artifact (value/metric/device/commit/mtime) alongside the
    error, not a bare 0.0 — BENCH_r05.json depends on this path."""
    sys.path.insert(0, REPO)
    import bench

    lk = bench.last_known_good()
    assert lk is not None, "tpu_watch/ has committed clean artifacts"
    assert lk["value"] > 0 and lk["metric"] and lk["source"]
    assert lk.get("measured_at_commit"), "nearest-commit stamp missing"

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.fail("m", "tpu-unreachable", "probe timed out")
    out = json.loads(buf.getvalue())
    assert out["error"] == "tpu-unreachable" and out["value"] == 0.0
    assert out["last_known"]["value"] == lk["value"]


def test_decode_ablate_tiny_all_groups():
    r = run_tool("tools/decode_ablate.py", "--tiny")
    assert r.returncode == 0, r.stderr[-2000:]
    for marker in ("full", "no-attn", "kv-int8", "seq-kernel", "kv8@s64",
                   "page=256", "roofline"):
        assert marker in r.stdout, f"missing {marker!r} in:\n{r.stdout}"
    assert "FAILED" not in r.stdout


def test_decode_ablate_rejects_unknown_group():
    r = run_tool("tools/decode_ablate.py", "--tiny", "--variants", "nope")
    assert r.returncode != 0
    assert "unknown variant group" in (r.stdout + r.stderr)


def test_kernel_bench_tiny():
    r = run_tool("tools/kernel_bench.py", "--tiny")
    assert r.returncode == 0, r.stderr[-2000:]
    for marker in ("grid", "seq", "grid-int8", "seq-int8"):
        assert marker in r.stdout
    assert "FAILED" not in r.stdout


def test_fleet_bench_tiny():
    r = run_tool("tools/fleet_bench.py", "--tiny")
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, f"no json line in:\n{r.stdout}"
    d = json.loads(lines[-1])
    assert "metric" in d and "value" in d


def test_stall_watchdog_state_machine(monkeypatch):
    """Trips only on (no progress >= stall_s) AND probe_fails consecutive
    failed probes spaced probe_gap_s apart; any progress or good probe
    resets."""
    import bench

    clock = {"t": 1000.0}
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock["t"])
    alive = {"ok": False}
    wd = bench.StallWatchdog(stall_s=400, probe_gap_s=100, probe_fails=3,
                             prober=lambda: alive["ok"])

    assert wd.stalled_and_dead((1, 0)) is False       # first observation
    clock["t"] += 500
    assert wd.stalled_and_dead((2, 0)) is False       # progress resets
    # now stall: same progress tuple for > stall_s
    clock["t"] += 399
    assert wd.stalled_and_dead((2, 0)) is False       # under threshold
    clock["t"] += 2                                   # 401s stalled
    assert wd.stalled_and_dead((2, 0)) is False       # fail #1
    assert wd.stalled_and_dead((2, 0)) is False       # gap not elapsed
    clock["t"] += 101
    assert wd.stalled_and_dead((2, 0)) is False       # fail #2
    clock["t"] += 101
    alive["ok"] = True
    assert wd.stalled_and_dead((2, 0)) is False       # good probe resets
    alive["ok"] = False
    clock["t"] += 101
    assert wd.stalled_and_dead((2, 0)) is False       # fail #1 again
    clock["t"] += 101
    assert wd.stalled_and_dead((2, 0)) is False       # fail #2
    clock["t"] += 101
    assert wd.stalled_and_dead((2, 0)) is True        # fail #3: trip
    # progress mid-stall fully resets even after a trip-level count
    clock["t"] += 10
    assert wd.stalled_and_dead((3, 0)) is False


def test_probe_device_ownership_modes(monkeypatch):
    """REVAL_TPU_EXCLUSIVE_DEVICE semantics: an exclusive-ownership chip
    is never probed by a second jax process; a watcher verdict only
    counts while the watcher's markers are FRESH (a leftover stale
    probe.log from a dead watcher must not read as 'wedged')."""
    import bench

    now = 1_000_000.0
    mtimes: dict[str, float] = {}
    spawned = []
    monkeypatch.setattr(bench.time, "time", lambda: now)

    def fake_getmtime(p):
        try:
            return mtimes[bench.os.path.basename(p)]
        except KeyError:
            raise OSError(2, "No such file", p)

    monkeypatch.setattr(bench.os.path, "getmtime", fake_getmtime)

    class _R:
        returncode = 1

    def fake_run(*a, **kw):
        spawned.append(a)
        return _R()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)

    # explicit exclusive: healthy, never spawns — markers irrelevant
    monkeypatch.setenv("REVAL_TPU_EXCLUSIVE_DEVICE", "1")
    assert bench.StallWatchdog._probe_device() is True
    # auto + no watcher markers at all: exclusive assumption
    monkeypatch.setenv("REVAL_TPU_EXCLUSIVE_DEVICE", "auto")
    assert bench.StallWatchdog._probe_device() is True
    # auto + live watcher, fresh ALIVE heartbeat: healthy
    mtimes["ALIVE"] = mtimes["probe.log"] = now - 10
    assert bench.StallWatchdog._probe_device() is True
    # auto + live watcher (fresh probe.log) with ALIVE gone: the
    # watcher's wedged verdict
    del mtimes["ALIVE"]
    assert bench.StallWatchdog._probe_device() is False
    # auto + DEAD watcher (only a stale probe.log left behind): not a
    # verdict — exclusive assumption again, never a false 'wedged'
    mtimes["probe.log"] = now - 7200
    assert bench.StallWatchdog._probe_device() is True
    assert spawned == []               # no second jax process, ever
    # explicit tunneled/shared: a LIVE watcher's verdict takes
    # precedence over the subprocess probe...
    monkeypatch.setenv("REVAL_TPU_EXCLUSIVE_DEVICE", "0")
    mtimes["ALIVE"] = now - 10
    assert bench.StallWatchdog._probe_device() is True
    assert spawned == []
    # ...and only without one does mode 0 spawn the probe
    del mtimes["ALIVE"]
    assert bench.StallWatchdog._probe_device() is False
    assert len(spawned) == 1


def test_chip_lock_serializes_and_never_deadlocks():
    import bench

    f1 = bench.acquire_chip_lock(max_wait_s=5)
    assert f1 is not None
    t0 = time.time()
    # a second contender (fresh fd) must wait, then proceed anyway
    f2 = bench.acquire_chip_lock(max_wait_s=1)
    assert f2 is not None and time.time() - t0 >= 1
    f1.close()
    f2.close()
