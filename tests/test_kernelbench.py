"""Self-healing kernel CI: the tier-1 chaos drill + unit coverage.

The fast tier runs the REAL harness three times on CPU (one shared
module fixture): a clean full-matrix round, the degradation drill
(wedge + timeout + flaky-device), and a perturbed regression-gate
round.  The drill pins the instrument's core promises:

- a wedged or timed-out cell degrades to a stale-marked entry carrying
  its last-known value + commit, with retries recorded — never a blind
  0.0 and never an aborted round;
- surviving cells still produce a valid leaderboard whose winner emits
  a loadable ``decide_defaults``-compatible serving-config pick;
- a seeded perturbation makes the regression gate exit 1 naming the
  cell with the incumbent-vs-HEAD delta.

Everything else (retry/stale/skip supervision, gate verdicts, schema
bites, chaos parsing, obs_report rendering, decide tiers) is unit-level
over injectable runners and synthesized artifacts — no subprocesses.
"""

from __future__ import annotations

import copy
import importlib.util
import json
import os
import time

import pytest

from reval_tpu.analysis import kernelbench as kb_lint
from reval_tpu.kernelbench import (SCHEMA, BenchShape, KernelCell,
                                   default_cells, incumbent_leaderboard,
                                   last_known_cell, main, regression_gate,
                                   run_round, supervise_cell,
                                   validate_leaderboard, write_leaderboard)
from reval_tpu.obs import metrics as obs_metrics
from reval_tpu.obs.metrics import MetricsRegistry
from reval_tpu.resilience import KERNEL_CELL_MODES, KernelCellChaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WEDGE_CELL = "pallas-swap-bf16-c2"
TIMEOUT_CELL = "xla-bf16-c4"
FLAKY_CELL = "pallas_seq-swap-bf16-c4"


def _load_tool(name: str):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"{name}_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _artifacts(out: str) -> list[str]:
    import glob

    return sorted(glob.glob(os.path.join(out, "kernelbench-*.json")))


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the tier-1 drill — THREE real CLI rounds shared by the module
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def drill(tmp_path_factory):
    """clean round -> chaos round (wedge + timeout + flaky) -> perturbed
    gate round; returns the three artifacts + exit codes + out dir."""
    out = str(tmp_path_factory.mktemp("kernelbench"))
    rc_clean = main(["--tiny", "--out-dir", out])
    arts = _artifacts(out)
    assert len(arts) == 1, "clean round wrote no artifact"
    clean = _load(arts[0])

    # wide noise band: CPU timing jitter must not flip THIS run's gate —
    # the exit-1 drill below uses a seeded 6x perturbation instead
    rc_chaos = main(["--tiny", "--out-dir", out, "--noise", "0.5",
                     "--cell-timeout", "8",
                     "--chaos-cell", f"wedge:{WEDGE_CELL}",
                     "--chaos-cell", f"timeout:{TIMEOUT_CELL}",
                     "--chaos-cell", f"flaky-device:{FLAKY_CELL}"])
    arts = _artifacts(out)
    assert len(arts) == 2, "chaos round wrote no artifact"
    chaos = _load(sorted(arts, key=os.path.getmtime)[-1])

    # the gate defends the newest NON-drill artifact (chaos rounds are
    # excluded as incumbents), so the regression is seeded into the
    # CLEAN round's winner cell
    victim = clean["summary"]["winner"]
    os.environ["REVAL_TPU_KERNELBENCH_PERTURB"] = f"{victim}=6.0"
    try:
        rc_gate = main(["--tiny", "--out-dir", out, "--cells", victim])
    finally:
        del os.environ["REVAL_TPU_KERNELBENCH_PERTURB"]
    arts = _artifacts(out)
    assert len(arts) == 3, "gate round wrote no artifact"
    gate = _load(sorted(arts, key=os.path.getmtime)[-1])
    return {"out": out, "clean": clean, "chaos": chaos, "gate": gate,
            "victim": victim, "rc": (rc_clean, rc_chaos, rc_gate)}


class TestDrill:
    def test_clean_round_runs_the_full_matrix(self, drill):
        art = drill["clean"]
        assert drill["rc"][0] == 0
        assert art["schema"] == SCHEMA and art["tiny"] is True
        names = {c.name for c in default_cells(tiny=True)}
        assert set(art["cells"]) == names
        for name, row in art["cells"].items():
            assert row["status"] == "run", (name, row)
            assert row["ms_per_step"] > 0
            assert row["retries"] == 0 and row["attempts"] == 1
        s = art["summary"]
        assert s["cells_run"] == len(names) and s["cells_stale"] == 0
        assert s["winner"] in names
        assert s["gate"]["status"] == "no-incumbent"
        # instrument-health telemetry rides the embedded registry snapshot
        assert (art["metrics"]["counters"][obs_metrics.KB_CELLS]
                == len(names))

    def test_wedged_and_timed_out_cells_degrade_to_stale(self, drill):
        art = drill["chaos"]
        assert drill["rc"][1] == 0, "a chaos round must never abort"
        clean_src = None
        for name, kill in ((WEDGE_CELL, "stall watchdog"),
                           (TIMEOUT_CELL, "budget")):
            row = art["cells"][name]
            assert row["status"] == "stale", (name, row)
            assert kill in row["error"]
            assert row["retries"] >= 1 and row["attempts"] >= 2
            lk = row["last_known"]
            assert lk["ms_per_step"] == \
                drill["clean"]["cells"][name]["ms_per_step"]
            assert lk["commit"] == drill["clean"]["commit"]
            clean_src = lk["source"]
            # the cardinal rule: a degraded cell is NEVER a 0.0
            assert "ms_per_step" not in row or row.get("ms_per_step")
        assert clean_src and clean_src.startswith("kernelbench-")
        assert art["chaos"][WEDGE_CELL] == "wedge"

    def test_flaky_device_recovers_with_retries_recorded(self, drill):
        row = drill["chaos"]["cells"][FLAKY_CELL]
        assert row["status"] == "run"
        assert row["ms_per_step"] > 0
        assert row["retries"] == 1 and row["attempts"] == 2

    def test_surviving_cells_produce_a_valid_leaderboard(self, drill):
        art = drill["chaos"]
        assert validate_leaderboard(art) == []
        s = art["summary"]
        assert s["cells_run"] >= 3 and s["cells_stale"] == 2
        assert s["winner"] is not None
        assert s["retries"] >= 3
        assert art["metrics"]["counters"][obs_metrics.KB_STALE] == 2
        assert art["metrics"]["counters"][obs_metrics.KB_RETRIES] >= 3

    def test_autotune_pick_roundtrips_through_decide_defaults(self, drill,
                                                              tmp_path):
        """The winner's pick is a loadable serving config: a (non-tiny)
        leaderboard in the watch dir makes decide_defaults persist
        autotune.json + decided_env.sh with the picked backend/dot/chunk
        — exactly what the dispatcher and runbook consume."""
        art = copy.deepcopy(drill["chaos"])
        pick = art["pick"]
        spec = art["cells"][art["summary"]["winner"]]["spec"]
        assert pick["REVAL_TPU_PAGED_BACKEND"] == spec["backend"]
        assert pick["env"]["REVAL_TPU_DECODE_CHUNK"] == str(spec["chunk"])
        assert pick["evidence"]["tier"] == "kernelbench"

        watch = tmp_path / "watch"
        watch.mkdir()
        # simulate the chip round this pick would come from: same schema,
        # not tiny, no chaos (drill debris never decides — tested below)
        art["tiny"] = False
        art["chaos"] = None
        with open(watch / "kernelbench-20990101-000000.json", "w") as f:
            json.dump(art, f)
        dd = _load_tool("decide_defaults")
        assert dd.main(["--watch", str(watch)]) == 0
        with open(watch / "autotune.json") as f:
            decision = json.load(f)
        assert decision["REVAL_TPU_PAGED_BACKEND"] == spec["backend"]
        assert decision["evidence"]["tier"] == "kernelbench"
        env_sh = (watch / "decided_env.sh").read_text()
        assert (f"export REVAL_TPU_DECODE_CHUNK={spec['chunk']}"
                in env_sh)
        assert (f"export REVAL_TPU_PAGED_BACKEND={spec['backend']}"
                in env_sh)

    def test_tiny_chaos_and_perturbed_artifacts_never_decide(self, drill,
                                                             tmp_path):
        dd = _load_tool("decide_defaults")
        for label, mutate in (
                ("tiny", lambda a: None),                      # stays tiny
                ("chaos", lambda a: a.update(tiny=False)),     # keeps chaos
                ("perturb", lambda a: a.update(
                    tiny=False, chaos=None,
                    perturb={"xla-bf16-c2": 6.0}))):
            watch = tmp_path / f"watch-{label}"
            watch.mkdir()
            art = copy.deepcopy(drill["chaos"])
            mutate(art)
            with open(watch / "kernelbench-20990101-000000.json", "w") as f:
                json.dump(art, f)
            assert dd.main(["--watch", str(watch)]) == 1, \
                f"{label} artifact must never become the serving default"

    def test_seeded_perturbation_trips_the_gate_exit_1(self, drill):
        assert drill["rc"][2] == 1
        gate = drill["gate"]["summary"]["gate"]
        assert gate["status"] == "regressed"
        assert gate["cell"] == drill["victim"]      # the gate NAMES the cell
        assert gate["incumbent_ms"] > 0 and gate["head_ms"] > 0
        assert gate["delta"] > gate["noise_band"]
        # a chaos drill never becomes the bar: the incumbent is round 1
        assert gate["incumbent_ms"] == \
            drill["clean"]["cells"][drill["victim"]]["ms_per_step"]
        assert gate["incumbent_commit"] == drill["clean"]["commit"]
        assert drill["gate"]["perturb"] == {drill["victim"]: 6.0}
        assert (drill["gate"]["metrics"]["counters"]
                [obs_metrics.KB_REGRESSIONS] == 1)

    def test_filtered_run_reports_unselected_as_skipped(self, drill):
        art = drill["gate"]
        assert validate_leaderboard(art) == []
        skipped = [n for n, r in art["cells"].items()
                   if r["status"] == "skipped"]
        assert len(skipped) == len(default_cells(tiny=True)) - 1
        for name in skipped:
            assert "not selected" in art["cells"][name]["reason"]

    def test_lint_pass_accepts_the_drill_artifacts(self, drill, tmp_path):
        root = tmp_path / "repo"
        (root / "tpu_watch").mkdir(parents=True)
        for i, path in enumerate(_artifacts(drill["out"])):
            with open(path) as f:
                data = f.read()
            (root / "tpu_watch" / f"kernelbench-0{i}.json").write_text(data)
        assert kb_lint.run({}, str(root)) == []

    def test_lint_pass_bites(self, drill, tmp_path):
        root = tmp_path / "repo"
        (root / "tpu_watch").mkdir(parents=True)
        bad = copy.deepcopy(drill["chaos"])
        vanished = drill["chaos"]["summary"]["winner"]
        del bad["cells"][vanished]
        bad["summary"]["winner"] = None
        bad.pop("pick", None)
        (root / "tpu_watch" / "kernelbench-00.json").write_text(
            json.dumps(bad))
        (root / "tpu_watch" / "kernelbench-01.json").write_text("{trunc")
        messages = [v.message for v in kb_lint.run({}, str(root))]
        assert any(vanished in m and "never dropped" in m for m in messages)
        assert any("unreadable" in m for m in messages)

    def test_obs_report_kernels_flags_stale_and_names_regression(
            self, drill, tmp_path):
        obs = _load_tool("obs_report")
        paths = _artifacts(drill["out"])
        text = obs.render_kernels(sorted(paths, key=os.path.getmtime))
        # stale cells render explicitly with provenance, never as fresh
        assert f"STALE {WEDGE_CELL}" in text
        assert drill["clean"]["commit"] in text
        assert "[CHAOS DRILL]" in text and "[PERTURBED" in text

        # a genuine cross-round per-cell regression is named FIRST:
        # synthesize round B = round A with one cell 2x slower
        a = copy.deepcopy(drill["clean"])
        b = copy.deepcopy(drill["clean"])
        slow = sorted(b["cells"])[0]
        b["cells"][slow]["ms_per_step"] *= 2
        pa, pb = tmp_path / "kb-a.json", tmp_path / "kb-b.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        text = obs.render_kernels([str(pa), str(pb)])
        assert f"first regression: kb-b.json ({slow}" in text

        # a tiny smoke interleaved between two chip rounds must not eat
        # the chip baseline (per-tier comparison state)
        a2, b2 = copy.deepcopy(a), copy.deepcopy(b)
        a2["tiny"] = b2["tiny"] = False
        pt = tmp_path / "kb-smoke.json"
        p2a, p2b = tmp_path / "kb-chip-a.json", tmp_path / "kb-chip-b.json"
        pt.write_text(json.dumps(a))
        p2a.write_text(json.dumps(a2))
        p2b.write_text(json.dumps(b2))
        text = obs.render_kernels([str(p2a), str(pt), str(p2b)])
        assert f"first regression: kb-chip-b.json ({slow}" in text

    def test_cli_emits_runbook_json_line(self, drill):
        """The runbook contract: ONE parseable JSON line on stdout with
        a nonzero value and no error key on a healthy round (subprocess
        shape — what `run kernelbench.json ... json` greps)."""
        import subprocess
        import sys

        r = subprocess.run(
            [sys.executable, "tools/kernelbench.py", "--tiny",
             "--out-dir", drill["out"], "--cells", drill["victim"],
             "--noise", "100"], cwd=REPO, capture_output=True, text=True,
            timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        assert len(lines) == 1, f"stdout must be ONE json line, got {lines}"
        d = json.loads(lines[0])
        assert d["value"] > 0 and "error" not in d
        assert d["winner"] == drill["victim"]


# ---------------------------------------------------------------------------
# units — no subprocesses
# ---------------------------------------------------------------------------

class TestTaxonomy:
    def test_names_unique_and_axes_covered(self):
        for tiny in (True, False):
            cells = default_cells(tiny)
            names = [c.name for c in cells]
            assert len(names) == len(set(names))
        full = default_cells(False)
        assert len(full) == 28
        assert {c.backend for c in full} == {"xla", "pallas", "pallas_seq",
                                             "ragged"}
        assert {c.pool for c in full} == {"bf16", "int8"}
        assert {c.chunk for c in full} == {8, 32}
        assert {c.dot for c in full if c.backend != "xla"} == {"swap",
                                                               "wide"}
        tiny = default_cells(True)
        assert len(tiny) == 8
        assert {WEDGE_CELL, TIMEOUT_CELL, FLAKY_CELL} <= {c.name
                                                          for c in tiny}

    def test_cell_roundtrip(self):
        cell = KernelCell("pallas_seq", "wide", "int8", 32)
        assert KernelCell.from_dict(cell.to_dict()) == cell
        assert cell.name == "pallas_seq-wide-int8-c32"
        assert KernelCell("xla", None, "bf16", 8).name == "xla-bf16-c8"


class TestChaos:
    def test_parse_roundtrip_and_rejects_typos(self):
        chaos = KernelCellChaos.parse(["wedge:a", "flaky-device:b"])
        assert chaos.rules == {"a": "wedge", "b": "flaky-device"}
        argv = chaos.to_argv()
        assert argv[::2] == ["--chaos-cell"] * 2     # child-CLI flag pairs
        assert KernelCellChaos.parse(argv[1::2]).rules == chaos.rules
        for bad in ("wedgd:a", "wedge", "wedge:", ":a"):
            with pytest.raises(ValueError):
                KernelCellChaos.parse([bad])
        assert set(KERNEL_CELL_MODES) == {"wedge", "timeout",
                                          "flaky-device"}

    def test_flaky_device_fails_then_recovers(self):
        chaos = KernelCellChaos({"c": "flaky-device"})
        with pytest.raises(ConnectionError):
            chaos.apply_in_child("c", attempt=0)
        chaos.apply_in_child("c", attempt=1)        # returns clean
        chaos.apply_in_child("other", attempt=0)    # untargeted: no-op

    def test_probe_override_only_simulates_dead_tunnel_for_wedge(self):
        chaos = KernelCellChaos({"w": "wedge", "t": "timeout"})
        assert chaos.device_probe_override("w")() is False
        assert chaos.device_probe_override("t") is None
        assert chaos.device_probe_override("other") is None


def _cell() -> KernelCell:
    return KernelCell("xla", None, "bf16", 2)


def _supervise(runner, out_dir, registry=None, attempts=2):
    return supervise_cell(
        _cell(), BenchShape.tiny(), tiny=True, out_dir=str(out_dir),
        hb_dir=str(out_dir), timeout_s=5.0, attempts=attempts,
        stall_s=1.0, probe_gap_s=0.1, probe_fails=2, poll_s=0.01,
        retry_delay_s=0.0, chaos=None,
        registry=registry if registry is not None else MetricsRegistry(),
        runner=runner, sleep=lambda s: None)


def _history_artifact(out_dir, ms=4.2, commit="abc1234", tiny=True):
    """A minimal prior leaderboard supplying last-known history."""
    art = {"schema": SCHEMA, "created_unix": time.time() - 60,
           "ts": "2026-08-03T00:00:00", "commit": commit, "tiny": tiny,
           "shape": BenchShape.tiny().to_dict(),
           "cells": {_cell().name: {
               "spec": _cell().to_dict(), "status": "run",
               "ms_per_step": ms, "gbps": 1.0, "attempts": 1,
               "retries": 0}},
           "summary": {"cells_run": 1, "cells_stale": 0,
                       "cells_skipped": 0, "retries": 0,
                       "winner": None, "gate": {"status": "no-incumbent"}}}
    return write_leaderboard(art, str(out_dir))


class TestSupervision:
    def test_transient_failure_retries_then_runs(self, tmp_path):
        calls = {"n": 0}

        def runner(cell, shape, **kw):
            calls["n"] += 1
            if kw["attempt"] == 0:
                raise TimeoutError("wedged once")
            return {"ms_per_step": 1.5, "gbps": 2.0}

        reg = MetricsRegistry()
        row = _supervise(runner, tmp_path, reg)
        assert row["status"] == "run" and row["ms_per_step"] == 1.5
        assert row["attempts"] == 2 and row["retries"] == 1
        assert reg.counter(obs_metrics.KB_RETRIES).value == 1
        assert calls["n"] == 2

    def test_exhausted_cell_with_history_goes_stale(self, tmp_path):
        src = _history_artifact(tmp_path, ms=4.2, commit="abc1234")

        def runner(cell, shape, **kw):
            raise TimeoutError("tunnel dead")

        reg = MetricsRegistry()
        row = _supervise(runner, tmp_path, reg)
        assert row["status"] == "stale"
        assert row["last_known"]["ms_per_step"] == 4.2
        assert row["last_known"]["commit"] == "abc1234"
        assert row["last_known"]["source"] == os.path.basename(src)
        assert row["retries"] == 1 and "tunnel dead" in row["error"]
        assert reg.counter(obs_metrics.KB_STALE).value == 1

    def test_exhausted_cell_without_history_skips_with_reason(self,
                                                              tmp_path):
        def runner(cell, shape, **kw):
            raise ConnectionError("no such device")

        row = _supervise(runner, tmp_path)
        assert row["status"] == "skipped"
        assert "no last-known value" in row["reason"]
        assert "no such device" in row["reason"]

    def test_application_errors_do_not_retry(self, tmp_path):
        calls = {"n": 0}

        def runner(cell, shape, **kw):
            calls["n"] += 1
            raise ValueError("a bug, not a wedge")

        row = _supervise(runner, tmp_path, attempts=3)
        assert calls["n"] == 1, "non-transport errors must not burn retries"
        assert row["status"] == "skipped"


class TestHistory:
    def test_last_known_never_crosses_tiers_or_reads_perturbed(self,
                                                               tmp_path):
        _history_artifact(tmp_path / "full", ms=9.9, tiny=False)
        assert last_known_cell(_cell().name, str(tmp_path / "full"),
                               tiny=True) is None
        p = _history_artifact(tmp_path / "pert", ms=9.9, tiny=True)
        obj = _load(p)
        obj["perturb"] = {_cell().name: 6.0}
        with open(p, "w") as f:
            json.dump(obj, f)
        assert last_known_cell(_cell().name, str(tmp_path / "pert"),
                               tiny=True) is None

    def test_stale_rows_chain_their_last_known_forward(self, tmp_path):
        _history_artifact(tmp_path, ms=4.2, commit="abc1234")
        mid = {"schema": SCHEMA, "created_unix": time.time() - 30,
               "ts": "t", "commit": "def5678", "tiny": True,
               "shape": BenchShape.tiny().to_dict(),
               "cells": {_cell().name: {
                   "spec": _cell().to_dict(), "status": "stale",
                   "error": "TimeoutError: wedged", "attempts": 2,
                   "retries": 1,
                   "last_known": {"ms_per_step": 4.2, "gbps": 1.0,
                                  "commit": "abc1234", "ts": "t0",
                                  "source": "kernelbench-old.json"}}},
               "summary": {"cells_run": 0, "cells_stale": 1,
                           "cells_skipped": 0, "retries": 1,
                           "winner": None,
                           "gate": {"status": "no-incumbent"}}}
        write_leaderboard(mid, str(tmp_path))
        lk = last_known_cell(_cell().name, str(tmp_path), tiny=True)
        # the chain carries the ORIGINAL measurement's commit forward
        assert lk["commit"] == "abc1234" and lk["ms_per_step"] == 4.2


class TestGate:
    def _incumbent(self, tmp_path, ms=4.0):
        path = _history_artifact(tmp_path, ms=ms, commit="inc0001")
        obj = _load(path)
        obj["summary"]["winner"] = _cell().name
        with open(path, "w") as f:
            json.dump(obj, f)
        return incumbent_leaderboard(str(tmp_path), tiny=True)

    def _head(self, status="run", ms=4.1):
        row = {"spec": _cell().to_dict(), "status": status, "attempts": 1,
               "retries": 0}
        if status == "run":
            row["ms_per_step"] = ms
        return {_cell().name: row}

    def test_within_noise_ok_beyond_noise_regressed(self, tmp_path):
        inc = self._incumbent(tmp_path)
        assert regression_gate(inc, self._head(ms=4.4), 0.15)["status"] \
            == "ok"
        gate = regression_gate(inc, self._head(ms=5.0), 0.15)
        assert gate["status"] == "regressed"
        assert gate["cell"] == _cell().name
        assert gate["incumbent_commit"] == "inc0001"
        assert gate["delta"] == pytest.approx(0.25)

    def test_chaos_and_perturbed_rounds_are_never_the_incumbent(
            self, tmp_path):
        path = _history_artifact(tmp_path, ms=4.0)
        obj = _load(path)
        obj["summary"]["winner"] = _cell().name
        obj["chaos"] = {_cell().name: "wedge"}
        with open(path, "w") as f:
            json.dump(obj, f)
        assert incumbent_leaderboard(str(tmp_path), tiny=True) is None
        obj["chaos"] = None
        obj["perturb"] = {_cell().name: 6.0}
        with open(path, "w") as f:
            json.dump(obj, f)
        assert incumbent_leaderboard(str(tmp_path), tiny=True) is None

    def test_blind_instrument_is_not_a_verdict(self, tmp_path):
        inc = self._incumbent(tmp_path)
        gate = regression_gate(inc, self._head(status="stale"), 0.15)
        assert gate["status"] == "instrument-blind"
        assert regression_gate(None, self._head(), 0.15)["status"] \
            == "no-incumbent"

    def test_faster_head_is_ok(self, tmp_path):
        inc = self._incumbent(tmp_path)
        assert regression_gate(inc, self._head(ms=2.0), 0.15)["status"] \
            == "ok"


class TestValidate:
    def _valid(self) -> dict:
        cells = {}
        for c in default_cells(tiny=True):
            cells[c.name] = {"spec": c.to_dict(), "status": "run",
                             "ms_per_step": 3.0, "gbps": 1.0,
                             "attempts": 1, "retries": 0}
        winner = default_cells(tiny=True)[0].name
        return {"schema": SCHEMA, "tiny": True,
                "shape": BenchShape.tiny().to_dict(), "cells": cells,
                "summary": {"cells_run": 6, "cells_stale": 0,
                            "cells_skipped": 0, "retries": 0,
                            "winner": winner, "gate": {"status": "ok"}},
                "pick": {"REVAL_TPU_PAGED_BACKEND": "xla",
                         "REVAL_TPU_KERNEL_DOT": "swap",
                         "env": {"REVAL_TPU_DECODE_CHUNK": "2"},
                         "bench_args": {}, "scope": {},
                         "evidence": {}}}

    def test_valid_artifact_passes(self):
        assert validate_leaderboard(self._valid()) == []

    def test_zero_measurement_bites(self):
        art = self._valid()
        name = art["summary"]["winner"]
        art["cells"][name]["ms_per_step"] = 0.0
        assert any("blind 0.0" in e for e in validate_leaderboard(art))

    def test_stale_without_commit_or_value_bites(self):
        art = self._valid()
        name = sorted(art["cells"])[1]
        art["cells"][name] = {"spec": art["cells"][name]["spec"],
                              "status": "stale", "error": "x",
                              "attempts": 2, "retries": 1,
                              "last_known": {"ms_per_step": 2.0}}
        assert any("carries no commit" in e
                   for e in validate_leaderboard(art))
        art["cells"][name]["last_known"] = {}
        assert any("last-known ms_per_step" in e
                   for e in validate_leaderboard(art))

    def test_vanished_cell_and_reasonless_skip_bite(self):
        art = self._valid()
        gone = sorted(n for n in art["cells"]
                      if n != art["summary"]["winner"])[0]
        del art["cells"][gone]
        assert any(gone in e and "never dropped" in e
                   for e in validate_leaderboard(art))
        art = self._valid()
        name = sorted(n for n in art["cells"]
                      if n != art["summary"]["winner"])[0]
        art["cells"][name] = {"spec": art["cells"][name]["spec"],
                              "status": "skipped"}
        assert any("without a reason" in e
                   for e in validate_leaderboard(art))

    def test_winner_and_pick_consistency_bite(self):
        art = self._valid()
        art["cells"][art["summary"]["winner"]]["status"] = "stale"
        assert any("not a fresh run cell" in e
                   for e in validate_leaderboard(art))
        art = self._valid()
        art["pick"]["REVAL_TPU_PAGED_BACKEND"] = "pallas"   # winner is xla
        assert any("does not match winner" in e
                   for e in validate_leaderboard(art))
        art = self._valid()
        del art["pick"]
        assert any("no serving-config pick" in e
                   for e in validate_leaderboard(art))

    def test_wrong_schema_is_terminal(self):
        assert validate_leaderboard({"schema": "nope"}) \
            == ["schema 'nope' != expected 'reval-kernelbench-v1'"]


class TestRunRoundUnits:
    def test_unknown_cell_selection_raises(self, tmp_path):
        with pytest.raises(ValueError, match="unknown cell"):
            run_round(tiny=True, select=["no-such-cell"],
                      out_dir=str(tmp_path),
                      runner=lambda *a, **k: {"ms_per_step": 1.0})

    def test_typoed_chaos_cell_raises_instead_of_running_clean(self,
                                                               tmp_path):
        with pytest.raises(ValueError, match="unknown cell"):
            run_round(tiny=True, out_dir=str(tmp_path),
                      chaos=KernelCellChaos({"xla-bf16-c3": "wedge"}),
                      runner=lambda *a, **k: {"ms_per_step": 1.0})

    def test_round_with_injected_runner_never_spawns(self, tmp_path):
        """The whole matrix through an in-process runner: artifact shape,
        ordering, winner, pick — no subprocesses, no jax."""
        ms = {c.name: 10.0 - i for i, c in
              enumerate(default_cells(tiny=True))}

        def runner(cell, shape, **kw):
            return {"ms_per_step": ms[cell.name], "gbps": 1.0,
                    "device": "cpu", "platform": "cpu"}

        art = run_round(tiny=True, out_dir=str(tmp_path), runner=runner,
                        sleep=lambda s: None)
        assert validate_leaderboard(art) == []
        assert list(art["cells"]) == [c.name
                                      for c in default_cells(tiny=True)]
        assert art["summary"]["winner"] == min(ms, key=ms.get)
        assert art["pick"]["evidence"]["cell"] == art["summary"]["winner"]
