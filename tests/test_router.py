"""Fleet router: prefix-affinity routing, failover, and the chaos drill.

Everything runs host-only: mock replicas (``serve --mock`` servers in
``echo`` mode — responses are a deterministic function of the prompt, so
"bit-identical regardless of which replica answered" is a real check)
behind a real :class:`FleetRouter` over real HTTP.

The headline is the chaos drill (ISSUE 7 acceptance): kill one of two
replicas mid-``fleet``, watch the router re-route, finish with ZERO lost
prompts, run ``fleet --resume`` against the intact journal, and diff the
task logs byte-for-byte against a single-replica run — plus the
federated ``/metrics`` accounting the ejection and failover.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from reval_tpu.inference.client import HTTPClientBackend
from reval_tpu.obs import metrics as obs_metrics
from reval_tpu.obs.metrics import parse_prometheus
from reval_tpu.serving import FleetRouter, serve_config
from reval_tpu.serving.router import (HashRing, affinity_key,
                                      federate_metrics, load_affinity_table)

TEMPLATE_A = "few-shot template alpha | " * 40
TEMPLATE_B = "few-shot template bravo | " * 40

FAST_RETRY = {"max_attempts": 10, "base_delay": 0.02,
              "max_delay": 0.3, "jitter": 0.1}


def make_replica(port=0, **cfg):
    base = {"mock": True, "mock_echo": True}
    base.update(cfg)
    return serve_config(base, port=port).start()


def make_router(servers, **kw):
    kw.setdefault("health_interval_s", 0.05)
    kw.setdefault("cooldown_s", 0.4)
    kw.setdefault("eject_fails", 2)
    router = FleetRouter([f"127.0.0.1:{s.port}" for s in servers],
                         port=0, **kw)
    return router.start()


def wait_router_ready(router, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.readiness()["ready"]:
            return
        time.sleep(0.02)
    raise AssertionError("router never became ready")


def hard_kill(server) -> None:
    """A crash, not a drain: the listener dies under its in-flight
    sockets; the session driver is left running (daemon) like a real
    kill -9 leaves no one to clean up."""
    server._httpd.shutdown()
    server._httpd.server_close()


def post_router(router, prompt, rid=None, max_tokens=64, timeout=30,
                extra=None):
    body = {"prompt": prompt, "max_tokens": max_tokens}
    body.update(extra or {})
    headers = {"Content-Type": "application/json"}
    if rid:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/v1/completions",
        data=json.dumps(body).encode(), headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def router_samples(router):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/metrics", timeout=10) as r:
        return parse_prometheus(r.read().decode())


def prompt_targeting(router, replica_id) -> str:
    """A prompt whose hash-ring PRIMARY is ``replica_id`` — the
    variation sits INSIDE the affinity window (a distinct "template"
    per candidate), because anything past the window cannot move the
    key by construction."""
    window = router.window_chars
    for i in range(4096):
        p = f"targeted template {i} | " + TEMPLATE_A
        if router._ring.order(affinity_key(p, window))[0] == replica_id:
            return p
    raise AssertionError(f"no prompt hashes to {replica_id}")


# ---------------------------------------------------------------------------
# Pure pieces: ring, affinity key, federation, table loading
# ---------------------------------------------------------------------------

def test_hash_ring_orders_all_members_and_is_stable_under_loss():
    members = [f"127.0.0.1:{3000 + i}" for i in range(4)]
    ring = HashRing(members, vnodes=64)
    keys = [affinity_key(f"template {i} " * 30, 512) for i in range(200)]
    lost = members[1]
    for key in keys:
        order = ring.order(key)
        assert sorted(order) == sorted(members)     # every member, once
        assert order == ring.order(key)             # deterministic
        # consistent hashing: removing one member must not move any key
        # whose primary was someone else
        survivors = [m for m in order if m != lost]
        if order[0] != lost:
            assert survivors[0] == order[0]


def test_affinity_key_windows_the_template():
    window = len(TEMPLATE_A) - 10
    a1 = affinity_key(TEMPLATE_A + "probe one", window)
    a2 = affinity_key(TEMPLATE_A + "a completely different suffix", window)
    b = affinity_key(TEMPLATE_B + "probe one", window)
    assert a1 == a2                 # same template → same replica
    assert a1 != b                  # distinct templates spread


def test_federate_metrics_sums_counters_and_buckets_takes_last_gauge():
    a = ("# HELP reval_requests_total x\n# TYPE reval_requests_total counter\n"
         "reval_requests_total 3\n"
         "# HELP g x\n# TYPE g gauge\ng 5\n"
         "# HELP h x\n# TYPE h histogram\n"
         'h_bucket{le="1"} 2\nh_bucket{le="+Inf"} 3\nh_sum 1.5\nh_count 3\n')
    b = ("# HELP reval_requests_total x\n# TYPE reval_requests_total counter\n"
         "reval_requests_total 4\n"
         "# HELP g x\n# TYPE g gauge\ng 9\n"
         "# HELP h x\n# TYPE h histogram\n"
         'h_bucket{le="1"} 1\nh_bucket{le="+Inf"} 1\nh_sum 0.25\nh_count 1\n')
    merged = federate_metrics([a, b])
    samples = parse_prometheus(merged)      # must re-parse cleanly
    assert samples["reval_requests_total"] == 7
    assert samples["g"] == 9                # gauge: last merged wins
    assert samples['h_bucket{le="1"}'] == 3
    assert samples['h_bucket{le="+Inf"}'] == 4
    assert samples["h_sum"] == 1.75
    assert samples["h_count"] == 4
    with pytest.raises(ValueError):
        federate_metrics(["not an exposition {{{"])


def test_affinity_table_validation_and_placement():
    table = {"format": "reval-affinity-v1", "window_chars": 200,
             "tasks": {"coverage": {"template_chars": 400, "key": "0a1b2c3d"},
                       "path": {"template_chars": 250, "key": "deadbeef"}}}
    assert load_affinity_table(dict(table))["window_chars"] == 200
    for bad in ({}, {"format": "v0"}, {"format": "reval-affinity-v1",
                                       "window_chars": 0}):
        with pytest.raises(ValueError):
            load_affinity_table(bad)
    srv = make_replica()
    try:
        router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                             affinity_table=table)
        assert router.window_chars == 200
        status = router.statusz()
        placement = status["affinity"]["placement"]
        assert set(placement) == {"coverage", "path"}
        assert placement["coverage"]["replica"] == f"127.0.0.1:{srv.port}"
        router.shutdown()
    finally:
        srv.shutdown()


def test_replica_forward_strikes_survive_clean_health_polls():
    """A replica whose listener answers /readyz while its forwards die
    must still eject on the forward strike count — clean polls reset
    only their own counter."""
    from reval_tpu.serving.router import _Replica

    rep = _Replica("r", "http://x", eject_fails=3, cooldown_s=1.0)
    for i in range(2):
        grant = rep.try_acquire()
        assert rep.release(grant, "fail", "boom") == ()
        assert rep.note_health(True, True, {}) == ()    # poll must not heal
    grant = rep.try_acquire()
    assert rep.release(grant, "fail", "boom") == ("ejected",)
    assert rep.snapshot()["state"] == "ejected"
    # conversely, poll strikes accumulate on their own counter
    rep2 = _Replica("r2", "http://x", eject_fails=2, cooldown_s=1.0)
    assert rep2.note_health(False, False, None, "dead") == ()
    assert rep2.note_health(False, False, None, "dead") == ("ejected",)


def test_half_open_gate_admits_exactly_one_probe():
    """A pre-ejection forward finishing must not re-open the half-open
    gate: only the probe's own release closes it."""
    from reval_tpu.serving.router import _Replica

    clock = {"t": 0.0}
    rep = _Replica("r", "http://x", eject_fails=1, cooldown_s=5.0,
                   clock=lambda: clock["t"])
    old = rep.try_acquire()             # long-running pre-ejection forward
    assert old == "normal"
    bad = rep.try_acquire()
    rep.release(bad, "fail", "boom")    # ejects (eject_fails=1)
    assert rep.snapshot()["state"] == "ejected"
    clock["t"] = 10.0                   # cooldown elapsed
    probe = rep.try_acquire()
    assert probe == "probe"
    # the OLD forward dying must NOT clear the probe gate: with the gate
    # wrongly re-opened, every request past cooldown would be admitted
    # as an extra concurrent "probe" against the possibly-dead replica
    rep.release(old, "fail", "old forward died")
    clock["t"] = 20.0
    assert rep.try_acquire() is None    # the one probe is still out
    # only the probe's own resolution closes the gate
    rep.release(probe, "ok")
    assert rep.snapshot()["state"] == "healthy"
    assert rep.try_acquire() == "normal"


def test_metrics_federation_skips_unparseable_replica():
    """One replica answering /metrics with garbage (a proxy error page)
    must not take the fleet scrape down."""
    import http.server

    class Garbage(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            # every route, /readyz included, answers an HTML error page:
            # the replica never reads as ready (so the POST below routes
            # to the real one) and its /metrics text must be SKIPPED by
            # the federation, not crash it
            body = b"<html>502 Bad Gateway</html>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    garbage = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Garbage)
    g_thread = threading.Thread(target=garbage.serve_forever, daemon=True)
    g_thread.start()
    srv = make_replica()
    router = FleetRouter(
        [f"127.0.0.1:{srv.port}", f"127.0.0.1:{garbage.server_address[1]}"],
        port=0, health_interval_s=0.05).start()
    try:
        wait_router_ready(router)
        post_router(router, "one real request")
        samples = router_samples(router)        # must parse: garbage skipped
        assert samples["reval_requests_total"] >= 1
        assert samples[obs_metrics.ROUTER_REQUESTS] >= 1
    finally:
        router.shutdown()
        garbage.shutdown()
        garbage.server_close()
        srv.shutdown()


# ---------------------------------------------------------------------------
# Routing behavior over live replicas
# ---------------------------------------------------------------------------

def test_template_affinity_pins_each_template_to_one_replica():
    servers = [make_replica() for _ in range(2)]
    router = make_router(servers, window_chars=len(TEMPLATE_A) - 5)
    try:
        wait_router_ready(router)
        for template in (TEMPLATE_A, TEMPLATE_B):
            before = [s._session.engine.stats.prompts for s in servers]
            for i in range(4):
                post_router(router, template + f"probe {i}")
            served = [s._session.engine.stats.prompts - b
                      for s, b in zip(servers, before)]
            # one replica took all four; the other none — the warm-cache
            # invariant routing exists for
            assert sorted(served) == [0, 4], served
        samples = router_samples(router)
        assert samples[obs_metrics.ROUTER_REQUESTS] == 8
        assert samples[obs_metrics.ROUTER_ROUTED] == 8
        assert samples.get(obs_metrics.ROUTER_FAILOVERS, 0) == 0
    finally:
        router.shutdown()
        for s in servers:
            s.shutdown()


def test_request_id_passes_through_and_is_minted_when_absent():
    servers = [make_replica()]
    router = make_router(servers)
    try:
        wait_router_ready(router)
        _, headers = post_router(router, "p", rid="drill-rid-42")
        assert headers.get("X-Request-Id") == "drill-rid-42"
        _, headers = post_router(router, "p")
        # the replica minted one; the router must surface it
        assert headers.get("X-Request-Id")
    finally:
        router.shutdown()
        servers[0].shutdown()


def test_client_error_passes_through_without_failover():
    servers = [make_replica() for _ in range(2)]
    router = make_router(servers)
    try:
        wait_router_ready(router)
        with pytest.raises(urllib.error.HTTPError) as err:
            post_router(router, "p", extra={"max_tokens": -1})
        assert err.value.code == 400
        assert json.loads(err.value.read())["error"]["code"] == \
            "invalid_request"
        assert router_samples(router).get(
            obs_metrics.ROUTER_FAILOVERS, 0) == 0
    finally:
        router.shutdown()
        for s in servers:
            s.shutdown()


def test_replica_kill_fails_over_and_ejects_then_half_open_recovers():
    servers = [make_replica() for _ in range(2)]
    router = make_router(servers, eject_fails=2, cooldown_s=0.3)
    try:
        wait_router_ready(router)
        victim = servers[0]
        victim_id = f"127.0.0.1:{victim.port}"
        target = prompt_targeting(router, victim_id)
        out1, _ = post_router(router, target)
        hard_kill(victim)
        # the same prompt must still serve — transport failover — and
        # produce the same bytes (echo mode) from the surviving replica
        out2, _ = post_router(router, target)
        assert out2["choices"][0]["text"] == out1["choices"][0]["text"]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            states = {r["id"]: r["state"]
                      for r in router.statusz()["replicas"]}
            if states[victim_id] == "ejected":
                break
            time.sleep(0.02)
        assert states[victim_id] == "ejected"
        samples = router_samples(router)
        assert samples[obs_metrics.ROUTER_EJECTIONS] >= 1
        assert samples[obs_metrics.ROUTER_FAILOVERS] >= 1
        # resurrect the replica ON THE SAME PORT; after the cooldown the
        # health poller (or a half-open probe) must rejoin it
        revived = make_replica(port=victim.port)
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                states = {r["id"]: r["state"]
                          for r in router.statusz()["replicas"]}
                if states[victim_id] == "healthy":
                    break
                time.sleep(0.05)
            assert states[victim_id] == "healthy"
            assert router_samples(router)[obs_metrics.ROUTER_RECOVERIES] >= 1
            out3, _ = post_router(router, target)
            assert out3["choices"][0]["text"] == out1["choices"][0]["text"]
        finally:
            revived.shutdown()
    finally:
        router.shutdown()
        for s in servers[1:]:
            s.shutdown()


def test_all_replicas_dead_sheds_503_fleet_unavailable_with_retry_after():
    servers = [make_replica()]
    router = make_router(servers)
    try:
        wait_router_ready(router)
        hard_kill(servers[0])
        with pytest.raises(urllib.error.HTTPError) as err:
            post_router(router, "p")
        assert err.value.code == 503
        body = json.loads(err.value.read())
        assert body["error"]["code"] == "fleet_unavailable"
        assert err.value.headers.get("Retry-After")
        samples = router_samples(router)
        assert samples[obs_metrics.ROUTER_SHEDS] >= 1
        # /readyz goes unready with Retry-After — the handshake keeps
        # polling instead of treating the 503 as arrival
        with pytest.raises(urllib.error.HTTPError) as rdy:
            urllib.request.urlopen(
                f"http://127.0.0.1:{router.port}/readyz", timeout=5)
        assert rdy.value.code == 503
        assert rdy.value.headers.get("Retry-After")
    finally:
        router.shutdown()


def test_saturated_fleet_sheds_429_with_retry_after_and_recovers():
    # one slow replica with a 1-token watermark: while a long request
    # holds the queue, the next submission sheds 429 replica-side and the
    # router (sole replica busy) sheds fleet-wide with the same contract
    servers = [make_replica(mock_step_s=0.1, max_queued_tokens=1)]
    router = make_router(servers)
    try:
        wait_router_ready(router)
        slow = threading.Thread(
            target=lambda: post_router(router, "hold " * 50,
                                       max_tokens=200, timeout=60))
        slow.start()
        time.sleep(0.15)    # the hold request is mid-decode (the mock
                            # needs ≥3 ticks of 0.1 s for its response)
        with pytest.raises(urllib.error.HTTPError) as err:
            post_router(router, "shed me")
        assert err.value.code == 429
        assert json.loads(err.value.read())["error"]["code"] == "overloaded"
        assert float(err.value.headers.get("Retry-After")) >= 1
        slow.join(timeout=60)
        # under a retrying client, concurrent pressure converges: every
        # prompt eventually serves through the shed/backoff loop
        client = HTTPClientBackend(model_id="m", port=router.port, temp=0.0,
                                   prompt_type="direct",
                                   wait_for_server_s=15, retry=FAST_RETRY)
        outs = {}
        threads = [threading.Thread(
            target=lambda i=i: outs.update(
                {i: client.infer_one(f"pressure {i}")}))
            for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(outs) == 6
        assert router_samples(router)[obs_metrics.ROUTER_SHEDS] >= 1
    finally:
        router.shutdown()
        servers[0].shutdown()


def test_admin_drain_takes_replica_out_and_rejoin_restores_it():
    servers = [make_replica() for _ in range(2)]
    router = make_router(servers, window_chars=len(TEMPLATE_A) - 5)
    try:
        wait_router_ready(router)
        drained = f"127.0.0.1:{servers[0].port}"
        target = prompt_targeting(router, drained)

        def admin(route):
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}{route}",
                data=json.dumps({"replica": drained}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())

        assert admin("/admin/drain")["replica"]["state"] == "draining"
        before = servers[0]._session.engine.stats.prompts
        post_router(router, target)     # primary drained → sibling serves
        assert servers[0]._session.engine.stats.prompts == before
        assert router_samples(router)[obs_metrics.ROUTER_FAILOVERS] >= 1
        assert admin("/admin/rejoin")["replica"]["state"] == "healthy"
        deadline = time.monotonic() + 5
        while (time.monotonic() < deadline
               and not router._replicas[drained].is_ready()):
            time.sleep(0.02)
        post_router(router, target)
        assert servers[0]._session.engine.stats.prompts > before
    finally:
        router.shutdown()
        for s in servers:
            s.shutdown()


def test_streaming_passes_through_the_router():
    servers = [make_replica()]
    router = make_router(servers)
    try:
        wait_router_ready(router)
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/v1/completions",
            data=json.dumps({"prompt": "stream me", "max_tokens": 32,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/event-stream")
            raw = resp.read().decode()
        deltas = [json.loads(line[len("data: "):])
                  for line in raw.splitlines()
                  if line.startswith("data: ") and "[DONE]" not in line]
        # the stream ends with the receipt trailer, then [DONE]
        assert deltas[-1]["object"] == "reval.receipt"
        assert deltas[-1]["receipt"]
        text = "".join(c["text"] for d in deltas
                       for c in d.get("choices", ()))
        direct, _ = post_router(router, "stream me", max_tokens=32)
        assert text == direct["choices"][0]["text"]
        assert "data: [DONE]" in raw
    finally:
        router.shutdown()
        servers[0].shutdown()


class _FakeResp:
    status = 200

    def __init__(self, chunks):
        self.headers = {"Content-Type": "text/event-stream",
                        "X-Request-Id": "minted-by-replica"}
        self._chunks = list(chunks)

    def read1(self, n):
        item = self._chunks.pop(0) if self._chunks else b""
        if isinstance(item, Exception):
            raise item
        return item


class _FakeHandler:
    def __init__(self, die_on_write=False):
        self.sent: list[bytes] = []
        self.headers: dict = {}
        self.die_on_write = die_on_write
        outer = self

        class _W:
            def write(self, b):
                if outer.die_on_write:
                    raise OSError("client gone")
                outer.sent.append(b)

            def flush(self):
                pass

        self.wfile = _W()

    def send_response(self, status):
        self.status = status

    def send_header(self, k, v):
        self.headers[k] = v

    def end_headers(self):
        pass


def test_pipe_stream_outcome_semantics():
    """The strike accounting behind mid-stream failures: an upstream
    death BEFORE the first byte raises (the caller fails over — the
    client saw nothing); mid-stream it returns an error string (the
    replica takes the strike for the truncated 200); a CLIENT hangup is
    None (not the replica's fault); the replica-minted request id falls
    through to the stream headers."""
    pipe = FleetRouter._pipe_stream

    with pytest.raises(ConnectionResetError):
        pipe(_FakeHandler(), _FakeResp([ConnectionResetError("boom")]), None)

    h = _FakeHandler()
    err = pipe(h, _FakeResp([b"data: a\n\n",
                             ConnectionResetError("boom")]), None)
    assert err is not None and "mid-stream" in err
    assert h.sent == [b"data: a\n\n"]       # the truncated 200 went out
    assert h.headers["X-Request-Id"] == "minted-by-replica"

    h = _FakeHandler()
    assert pipe(h, _FakeResp([b"data: a\n\n", b""]), "caller-rid") is None
    assert h.headers["X-Request-Id"] == "caller-rid"

    assert pipe(_FakeHandler(die_on_write=True),
                _FakeResp([b"data: a\n\n", b""]), None) is None


def test_client_handshake_reports_router_degradation(capsys):
    servers = [make_replica() for _ in range(2)]
    router = make_router(servers)
    try:
        wait_router_ready(router)
        hard_kill(servers[0])
        # wait for the poller to see the corpse: the handshake line must
        # report the degraded count, and the fleet must still be READY
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and router.readiness()["replicas_ready"] != 1):
            time.sleep(0.02)
        client = HTTPClientBackend(model_id="m", port=router.port, temp=0.0,
                                   prompt_type="direct",
                                   wait_for_server_s=15, retry=FAST_RETRY)
        assert "1/2 replicas ready" in capsys.readouterr().out
        assert client.infer_many(["a", "b"])
    finally:
        router.shutdown()
        servers[1].shutdown()


# ---------------------------------------------------------------------------
# The chaos drill (the ISSUE 7 acceptance scenario)
# ---------------------------------------------------------------------------

def _run_fleet(results_dir, port, repeats=2, resume=False):
    from reval_tpu.fleet import FleetRunner

    backend = HTTPClientBackend(model_id="drill", port=port, temp=0.0,
                                prompt_type="direct", wait_for_server_s=30,
                                retry=FAST_RETRY)
    fleet = FleetRunner(dataset="humaneval", prompt_type="direct",
                        repeats=repeats, backend=backend,
                        results_dir=str(results_dir), progress=False,
                        run_consistency=False, max_items=2,
                        tasks=("coverage", "path"), resume=resume)
    try:
        return fleet.run()
    finally:
        backend.close()


def _task_logs(results_dir):
    """Per-task log CONTENTS, creation-ordered (filenames carry wall
    timestamps, so two identical runs differ in names, never bytes)."""
    logs = {}
    for task in ("coverage", "path"):
        d = os.path.join(str(results_dir), f"{task}@drill_direct_temp0.0")
        paths = sorted((os.path.join(d, f) for f in os.listdir(d)),
                       key=os.path.getctime)
        logs[task] = [open(p).read() for p in paths]
    return logs


def test_chaos_drill_replica_kill_zero_lost_prompts_bit_identical(tmp_path):
    """Kill one of two replicas mid-fleet: the run must finish with zero
    lost prompts (client retry + router failover), ``--resume`` must find
    a fully-journaled checkpoint, the task logs must be byte-identical
    to a single-replica run, and the federated /metrics must account the
    ejection + failover."""
    # -- baseline: single replica behind the same router topology --------
    base_srv = make_replica()
    base_router = make_router([base_srv])
    wait_router_ready(base_router)
    try:
        base_result = _run_fleet(tmp_path / "base", base_router.port)
    finally:
        base_router.shutdown()
        base_srv.shutdown()
    assert "lost_prompts" not in base_result

    # -- the drill: two replicas, one dies while the fleet is running ----
    servers = [make_replica() for _ in range(2)]
    router = make_router(servers, eject_fails=2, cooldown_s=30.0)
    wait_router_ready(router)
    killed = {}

    def assassin():
        # strike as soon as ANY replica has served a prompt — mid-run by
        # construction (the fleet still has prompts and a whole second
        # repeat to go)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            for srv in servers:
                if srv._session.engine.stats.prompts > 0:
                    hard_kill(srv)
                    killed["id"] = f"127.0.0.1:{srv.port}"
                    return
            time.sleep(0.002)

    try:
        hit = threading.Thread(target=assassin)
        hit.start()
        drill_result = _run_fleet(tmp_path / "drill", router.port)
        hit.join(timeout=60)

        # zero lost prompts: nothing took the INFER_FAILED sentinel
        assert "lost_prompts" not in drill_result
        assert killed, "the assassin never fired — drill exercised nothing"

        # resume against the intact journal: every chunk already scored,
        # so the resumed run skips straight through (no new inference,
        # no new log files)
        before_logs = _task_logs(tmp_path / "drill")
        resumed = _run_fleet(tmp_path / "drill", router.port, resume=True)
        assert len(resumed["repeats"]) == 2
        assert resumed["repeats"] == drill_result["repeats"]
        assert _task_logs(tmp_path / "drill") == before_logs

        # bit-identical greedy outputs regardless of which replica
        # answered (echo-mode responses are prompt-determined)
        assert _task_logs(tmp_path / "drill") == _task_logs(tmp_path / "base")
        assert drill_result["repeats"] == base_result["repeats"]

        # a forward whose ring-primary is the corpse must count a
        # failover (deterministic even after ejection)
        post_router(router, prompt_targeting(router, killed["id"]))
        samples = router_samples(router)     # federation still parses
        assert samples[obs_metrics.ROUTER_EJECTIONS] >= 1
        assert samples[obs_metrics.ROUTER_FAILOVERS] >= 1
        # one fused POST per repeat + the targeted probe (client retries
        # of a killed-mid-flight POST only add to this)
        assert samples[obs_metrics.ROUTER_REQUESTS] >= 3
        states = {r["id"]: r["state"] for r in router.statusz()["replicas"]}
        assert states[killed["id"]] == "ejected"
    finally:
        router.shutdown()
        for srv in servers:
            if killed.get("id") != f"127.0.0.1:{srv.port}":
                srv.shutdown()


# ---------------------------------------------------------------------------
# CLI smoke (the tier-1 canary) + affinity-table tool round trip
# ---------------------------------------------------------------------------

def test_router_cli_mock_smoke_with_replica_kill():
    r = subprocess.run(
        [sys.executable, "-m", "reval_tpu", "router", "--mock", "2",
         "--smoke", "8"],
        capture_output=True, text=True, timeout=150,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["served"] == 8
    assert summary["errors"] == 0
    assert summary["metrics_ok"] is True
    assert summary["killed_replica"] is True
    assert summary["ejections"] >= 1
    assert summary["router_requests"] >= 8


def test_prefix_stats_json_affinity_table_seeds_the_router(tmp_path):
    out_path = tmp_path / "affinity.json"
    r = subprocess.run(
        [sys.executable, "tools/prefix_stats.py", "--tiny",
         "--json", str(out_path)],
        capture_output=True, text=True, timeout=150,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stdout + r.stderr
    table = json.loads(out_path.read_text())
    assert table["format"] == "reval-affinity-v1"
    assert table["window_chars"] >= 16
    assert set(table["tasks"]) == {"coverage", "path", "state", "output"}
    for row in table["tasks"].values():
        assert row["template_chars"] >= 0
        int(row["key"], 16)
    # the stdout report carries the same block
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["affinity"]["window_chars"] == table["window_chars"]
    # and the router loads it as its ring seed
    srv = make_replica()
    try:
        router = FleetRouter([f"127.0.0.1:{srv.port}"], port=0,
                             affinity_table=str(out_path))
        assert router.window_chars == table["window_chars"]
        placement = router.statusz()["affinity"]["placement"]
        assert set(placement) == set(table["tasks"])
        router.shutdown()
    finally:
        srv.shutdown()


def test_watch_console_renders_router_fleet_view(capsys):
    """`reval_tpu watch` pointed at the ROUTER endpoint must render the
    federated fleet view (per-replica ready/ejected state, fleet req/s
    from the router's own counters) instead of failing on the router's
    /statusz shape (routers serve no /debugz)."""
    from reval_tpu.watch import run_watch

    servers = [make_replica(), make_replica()]
    router = make_router(servers)
    try:
        wait_router_ready(router)
        hard_kill(servers[1])          # one replica dies; poller ejects it
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            reps = {r["id"]: r for r in router.statusz()["replicas"]}
            if any(r["state"] == "ejected" for r in reps.values()):
                break
            time.sleep(0.05)
        post_router(router, "watch me", max_tokens=8)
        rc = run_watch(["--port", str(router.port), "--interval", "0.01",
                        "--iterations", "2", "--no-clear"])
    finally:
        router.shutdown()
        for srv in servers:
            srv.shutdown()
    assert rc == 0
    out = capsys.readouterr().out
    assert "ROUTER" in out and "replicas ready" in out
    assert "req/s" in out and "failovers" in out and "ejections" in out
    # both replica rows render, with the dead one visibly not healthy
    assert "healthy" in out and "ejected" in out
    assert out.count("reval_tpu watch") == 2
    # per-replica rows name both replica ids
    for rep in router.statusz()["replicas"]:
        assert str(rep["id"]) in out
