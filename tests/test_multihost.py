"""Two-process jax.distributed rig on CPU: the multihost replicate-mode
path (shard_for_host → infer_many → gather_strings → primary-only write)
actually executing with process_count == 2 — not just the single-process
degenerate case (round-1 verdict weak item 5).

Each worker is a real OS process; the coordinator runs over localhost.
"""

import pytest

pytestmark = pytest.mark.slow

import os
import subprocess
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid = int(sys.argv[1]); out_dir = sys.argv[2]; port = sys.argv[3]
    from reval_tpu.parallel.distributed import (
        ensure_initialized, gather_strings, is_primary_host, shard_for_host)
    ensure_initialized(coordinator_address="127.0.0.1:" + port,
                       num_processes=2, process_id=pid, strict=True)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == pid

    prompts = [f"prompt-{{i}}" for i in range(7)]       # odd: uneven shards
    shard, start = shard_for_host(prompts)
    assert len(shard) in (3, 4)

    from reval_tpu.inference.mock import MockBackend
    backend = MockBackend(prompt_type="direct")
    local = [f"[p{{pid}}@{{start}}] " + r
             for r in backend.infer_many(shard)]

    full = gather_strings(local)
    assert len(full) == 7, full
    # process order restores caller order: host 0's shard first
    assert full[0].startswith("[p0@0]") and full[-1].startswith("[p1@")

    if is_primary_host():
        with open(os.path.join(out_dir, "results.json"), "w") as f:
            json.dump(full, f)
    print("WORKER_OK", pid)
""")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_rig(script, tmp_path, nprocs: int = 2,
             extra_args: list | None = None) -> tuple[list, list]:
    port = str(_free_port())
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)           # default 1 CPU device per process
    procs = [subprocess.Popen([sys.executable, str(script), str(pid),
                               str(tmp_path), port] + (extra_args or []),
                              stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              text=True, env=env)
             for pid in range(nprocs)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    return procs, outs


def test_two_process_replicate_mode(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO))
    procs, outs = _run_rig(script, tmp_path)
    if any(p.returncode != 0 for p in procs):
        # the probed free port can be stolen before the coordinator binds
        # it; one retry with a fresh port covers that race
        procs, outs = _run_rig(script, tmp_path)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER_OK {pid}" in out
    # primary-only write: exactly one results file, with all 7 rows in order
    import json

    with open(tmp_path / "results.json") as f:
        full = json.load(f)
    assert len(full) == 7
    assert [r.split("] ", 1)[0] + "]" for r in full[:4]] == ["[p0@0]"] * 4


PP_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); out_dir = sys.argv[2]; port = sys.argv[3]
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                               num_processes=2, process_id=pid)
    assert jax.process_count() == 2

    from reval_tpu.inference.tpu.engine import TPUEngine
    from reval_tpu.inference.tpu.pp_engine import PipelinedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params
    from reval_tpu.parallel import make_mesh

    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                      hidden_size=64, intermediate_size=128, num_layers=4,
                      num_heads=4, num_kv_heads=2, head_dim=16)
    params = init_random_params(cfg, seed=0, dtype="float32")
    tok = ByteTokenizer()
    # pp=4 ring spanning 2 processes x 2 local devices: stage hops 1->2
    # cross the process boundary (gloo), exactly the multi-host shape
    eng = PipelinedTPUEngine(params, cfg, tok, batch_size=4,
                             max_seq_len=128, mesh=make_mesh(pp=4))
    outs = eng.generate(["def f(x):", "x = 1"], max_new_tokens=6,
                        temperature=0.0)
    if pid == 0:
        plain = TPUEngine(params, cfg, tok, batch_size=4, max_seq_len=128)
        want = plain.generate(["def f(x):", "x = 1"], max_new_tokens=6,
                              temperature=0.0)
        assert outs == want, (outs, want)
    print("WORKER_OK", pid)
""")


GLOBAL_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); out_dir = sys.argv[2]; port = sys.argv[3]
    jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                               num_processes=4, process_id=pid)
    assert jax.process_count() == 4
    assert len(jax.devices()) == 8, len(jax.devices())

    from reval_tpu.inference.tpu.engine import TPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params
    from reval_tpu.parallel import make_mesh

    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 61,
                      hidden_size=64, intermediate_size=128, num_layers=2,
                      num_heads=4, num_kv_heads=2, head_dim=16)
    params = init_random_params(cfg, seed=0, dtype="float32")
    tok = ByteTokenizer()
    prompts = ["def f(x):", "x = 1", "for i in range("]

    # the 70B launcher shape (tpu_vm_fleet.sh MULTIHOST=global): one model
    # over the JOINT 4-process x 2-device mesh, dp x tp; the batch spans
    # dp groups that live on DIFFERENT processes
    eng = TPUEngine(params, cfg, tok, batch_size=4, max_seq_len=128,
                    mesh=make_mesh(dp=2, tp=4))
    outs = eng.generate(prompts, max_new_tokens=6, temperature=0.0)

    # every host must hold the full gathered outputs, and they must match
    # a plain single-process local engine bit for bit
    plain = TPUEngine(params, cfg, tok, batch_size=4, max_seq_len=128)
    want = plain.generate(prompts, max_new_tokens=6, temperature=0.0)
    assert outs == want, (outs, want)
    print("WORKER_OK", pid)
""")


def test_four_process_global_mesh(tmp_path):
    """MULTIHOST=global backing (round-3 verdict item 7): a dp=2 x tp=4
    mesh spanning FOUR jax.distributed processes (2 local CPU devices
    each), generation outputs identical to the single-process engine on
    every host."""
    script = tmp_path / "global_worker.py"
    script.write_text(GLOBAL_WORKER.format(repo=REPO))
    procs, outs = _run_rig(script, tmp_path, nprocs=4)
    if any(p.returncode != 0 for p in procs):
        procs, outs = _run_rig(script, tmp_path, nprocs=4)  # port race retry
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER_OK {pid}" in out


def test_two_process_pipeline_ring(tmp_path):
    """The pp token ring crossing a REAL process boundary: a 4-stage
    pipeline over 2 jax.distributed CPU processes (2 local devices each),
    parity-checked against the single-process engine on process 0."""
    script = tmp_path / "pp_worker.py"
    script.write_text(PP_WORKER.format(repo=REPO))
    procs, outs = _run_rig(script, tmp_path)
    if any(p.returncode != 0 for p in procs):
        procs, outs = _run_rig(script, tmp_path)   # free-port race retry
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER_OK {pid}" in out


FLEET_GLOBAL_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); out_dir = sys.argv[2]; port = sys.argv[3]
    # the launcher env rig (tpu_vm_fleet.sh off-TPU path): the CLI's
    # ensure_initialized(strict=True) takes no explicit topology — it must
    # find it here
    os.environ["REVAL_TPU_COORDINATOR"] = "127.0.0.1:" + port
    os.environ["REVAL_TPU_NUM_PROCESSES"] = "2"
    os.environ["REVAL_TPU_PROCESS_ID"] = str(pid)

    import json
    cfg = {{"task": "coverage", "model_id": "fleet-global",
            "model_path": sys.argv[4], "dtype": "float32",
            "dataset": "humaneval", "prompt_type": "direct",
            "tasks": ["coverage"], "max_items": 2, "temp": 0.0,
            "num_chips": 4, "batch_size": 4,
            "results_dir": os.path.join(out_dir, "results")}}
    cfg_path = os.path.join(out_dir, f"fleet_cfg_{{pid}}.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    from reval_tpu import cli
    rc = cli.main(["fleet", "-i", cfg_path, "--repeats", "1",
                   "--multihost", "global"])
    assert not rc, rc
    assert jax.process_count() == 2, jax.process_count()
    print("WORKER_OK", pid)
""")


def test_fleet_cli_global_mode_two_processes(tmp_path):
    """The full MULTIHOST=global claim chain, CLI down: two
    `reval_tpu fleet --multihost global` processes join one
    jax.distributed rig via the launcher env vars, build ONE tp=4 static
    engine over the joint 2x2-device mesh, run the coverage task on a
    real (tiny) HF checkpoint, and only the primary host writes results."""
    import torch
    from tokenizers import Tokenizer, decoders, models as tok_models, pre_tokenizers
    from transformers import LlamaConfig, LlamaForCausalLM, PreTrainedTokenizerFast

    ckpt = tmp_path / "tiny-llama-fleet"
    torch.manual_seed(5)
    chars = [chr(i) for i in range(32, 127)] + ["\n", "\t"]
    vocab = {c: i for i, c in enumerate(chars)}
    vocab["<unk>"] = len(vocab); vocab["<eos>"] = len(vocab)
    hf_cfg = LlamaConfig(vocab_size=len(vocab), hidden_size=64,
                         intermediate_size=128, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=4096,
                         eos_token_id=vocab["<eos>"])
    LlamaForCausalLM(hf_cfg).eval().save_pretrained(ckpt, safe_serialization=True)
    tok = Tokenizer(tok_models.BPE(vocab=vocab, merges=[], unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Split("", "isolated")
    tok.decoder = decoders.Fuse()
    tok.save(str(ckpt / "tokenizer.json"))
    PreTrainedTokenizerFast(tokenizer_file=str(ckpt / "tokenizer.json"),
                            eos_token="<eos>",
                            unk_token="<unk>").save_pretrained(ckpt)

    script = tmp_path / "fleet_global_worker.py"
    script.write_text(FLEET_GLOBAL_WORKER.format(repo=REPO))
    procs, outs = _run_rig(script, tmp_path, nprocs=2, extra_args=[str(ckpt)])
    if any(p.returncode != 0 for p in procs):
        # port race retry — drop any partial first-attempt results or the
        # final one-log-file assert counts both attempts
        import shutil

        shutil.rmtree(tmp_path / "results", ignore_errors=True)
        procs, outs = _run_rig(script, tmp_path, nprocs=2,
                               extra_args=[str(ckpt)])
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER_OK {pid}" in out
    import glob

    logs = glob.glob(str(tmp_path / "results" / "**" / "*.jsonl"),
                     recursive=True)
    # primary-only write: one task, one repeat, ONE log file total
    assert len(logs) == 1, logs
