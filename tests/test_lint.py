"""reval-lint: the static analysis suite + runtime lock sanitizer.

Three layers under test (ISSUE 6):

1. the repo at HEAD is CLEAN under every pass (the tier-1 wiring — the
   analog of the old check_metrics test, now covering locks/hotpath/
   errors/env/metrics/events through one driver);
2. each pass BITES: a planted violating snippet is flagged (and its
   clean twin is not) — a lint that cannot fail is documentation;
3. the runtime lock sanitizer catches a planted lock-order inversion
   and an off-lock guarded write, and derives its audit maps from the
   same ``# guarded-by:`` annotations the static pass reads.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from reval_tpu.analysis import lockcheck  # noqa: E402
from reval_tpu.analysis.driver import PASSES, run_lint  # noqa: E402
from reval_tpu.env import ENV, env_flag, env_int, env_str  # noqa: E402


def plant(tmp_path, rel, text):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text)
    return path


def messages(report, pass_name=None):
    return [v.message for v in report.violations
            if pass_name is None or v.pass_name == pass_name]


# ---------------------------------------------------------------------------
# the repo at HEAD is clean (tier-1 entry point)
# ---------------------------------------------------------------------------

def test_repo_is_clean_under_all_passes():
    report = run_lint(REPO)
    assert report.ok, "\n".join(str(v) for v in report.violations)
    # the suppression ledger exists and every entry carries a reason
    assert all(s.reason for s in report.suppressions)


def test_driver_runs_fast():
    report = run_lint(REPO)
    assert report.elapsed_s < 10.0, (
        f"reval-lint took {report.elapsed_s:.1f}s — the <10s acceptance "
        f"bar exists so it stays cheap enough for tier 1")
    assert report.files > 50          # it actually walked the tree


def test_unknown_pass_rejected():
    with pytest.raises(ValueError, match="unknown lint pass"):
        run_lint(REPO, ["nonsense"])


# ---------------------------------------------------------------------------
# locks pass bites
# ---------------------------------------------------------------------------

LOCKY = '''import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []            # guarded-by: _lock
        self._count = 0             # guarded-by: _lock

    def good(self):
        with self._lock:
            self._items.append(1)
            self._count += 1

    def helper(self):               # lock-held: _lock
        return self._count
'''


def test_locks_clean_class_passes(tmp_path):
    plant(tmp_path, "reval_tpu/locky.py", LOCKY)
    report = run_lint(str(tmp_path), ["locks"])
    assert report.ok, messages(report)


def test_locks_flags_off_lock_access(tmp_path):
    plant(tmp_path, "reval_tpu/locky.py",
          LOCKY + '''
    def racy(self):
        return len(self._items)
''')
    report = run_lint(str(tmp_path), ["locks"])
    assert any("_items" in m and "outside" in m for m in messages(report))


def test_locks_flags_unclassified_mutable_state(tmp_path):
    plant(tmp_path, "reval_tpu/locky.py", LOCKY.replace(
        "self._count = 0             # guarded-by: _lock",
        "self._table = {}"))
    report = run_lint(str(tmp_path), ["locks"])
    assert any("_table" in m and "neither" in m for m in messages(report))


def test_locks_flags_typoed_lock_name(tmp_path):
    plant(tmp_path, "reval_tpu/locky.py", LOCKY.replace(
        "# guarded-by: _lock\n        self._count",
        "# guarded-by: _lokc\n        self._count"))
    report = run_lint(str(tmp_path), ["locks"])
    assert any("no such lock" in m for m in messages(report))


def test_locks_nested_function_resets_held_set(tmp_path):
    # a callback defined INSIDE a with block runs later: holding the
    # lock at definition time must not exempt the body
    plant(tmp_path, "reval_tpu/locky.py", LOCKY + '''
    def schedule(self):
        with self._lock:
            def later():
                return len(self._items)
            return later
''')
    report = run_lint(str(tmp_path), ["locks"])
    assert any("_items" in m and "outside" in m for m in messages(report))


def test_locks_writes_only_mode(tmp_path):
    plant(tmp_path, "reval_tpu/locky.py", '''import threading


class Stat:
    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0       # guarded-by: _lock (writes)

    def add(self):
        with self._lock:
            self._v += 1

    def read(self):
        return self._v      # lock-free read is the declared contract
''')
    report = run_lint(str(tmp_path), ["locks"])
    assert report.ok, messages(report)


# ---------------------------------------------------------------------------
# hotpath pass bites
# ---------------------------------------------------------------------------

def test_hotpath_flags_blocking_calls(tmp_path):
    plant(tmp_path, "reval_tpu/hot.py", '''import json
import time


def tick(state):   # hot-path
    time.sleep(0.1)
    return json.dumps(state)


def cold(state):
    return json.dumps(state)
''')
    report = run_lint(str(tmp_path), ["hotpath"])
    msgs = messages(report)
    assert any("time.sleep" in m for m in msgs)
    assert any("json.dumps" in m for m in msgs)
    assert all("'cold'" not in m for m in msgs)     # unmarked = uncovered


def test_hotpath_suppression_requires_reason(tmp_path):
    plant(tmp_path, "reval_tpu/hot.py", '''import time


def tick():   # hot-path
    # lint: allow(hotpath)
    time.sleep(0.1)
''')
    report = run_lint(str(tmp_path), ["hotpath"])
    assert any("without a reason" in m for m in messages(report))


def test_hotpath_suppression_with_reason_is_counted(tmp_path):
    plant(tmp_path, "reval_tpu/hot.py", '''import time


def tick():   # hot-path
    # lint: allow(hotpath) — deliberate pacing knob for tests
    time.sleep(0.1)
''')
    report = run_lint(str(tmp_path), ["hotpath"])
    assert report.ok
    assert len(report.suppressions) == 1
    assert "pacing knob" in report.suppressions[0].reason


# ---------------------------------------------------------------------------
# errors pass bites
# ---------------------------------------------------------------------------

def test_errors_flags_bare_runtimeerror_in_serving(tmp_path):
    plant(tmp_path, "reval_tpu/serving/handler.py", '''
def handle(req):
    if not req:
        raise ValueError("bad request")      # client error: allowed
    raise RuntimeError("engine fell over")   # untyped: banned
''')
    report = run_lint(str(tmp_path), ["errors"])
    msgs = messages(report)
    assert len(msgs) == 1 and "raise RuntimeError" in msgs[0]


def test_errors_ignores_non_serving_modules(tmp_path):
    plant(tmp_path, "reval_tpu/other.py",
          'def f():\n    raise RuntimeError("fine here")\n')
    report = run_lint(str(tmp_path), ["errors"])
    assert report.ok


# ---------------------------------------------------------------------------
# env pass bites + registry round-trip
# ---------------------------------------------------------------------------

def test_env_flags_raw_read_and_undeclared_name(tmp_path):
    plant(tmp_path, "reval_tpu/cfg.py", '''import os

from .env import env_str

A = os.environ.get("REVAL_TPU_WATCHDOG_S", "120")
B = env_str("REVAL_TPU_NOT_A_REAL_KNOB")
os.environ["REVAL_TPU_OBS"] = "0"            # a WRITE: legal
''')
    report = run_lint(str(tmp_path), ["env"])
    msgs = messages(report)
    assert any("raw os.environ.get('REVAL_TPU_WATCHDOG_S')" in m
               for m in msgs)
    assert any("REVAL_TPU_NOT_A_REAL_KNOB" in m and "not declared" in m
               for m in msgs)
    assert not any("REVAL_TPU_OBS" in m and "raw" in m for m in msgs)


def test_env_readme_round_trip_bites(tmp_path):
    # a planted README documenting a ghost var AND missing the real ones
    plant(tmp_path, "reval_tpu/mod.py", "x = 1\n")
    plant(tmp_path, "README.md",
          "| `REVAL_TPU_GHOST_KNOB` | 1 | not a real knob |\n")
    report = run_lint(str(tmp_path), ["env"])
    msgs = messages(report)
    assert any("REVAL_TPU_GHOST_KNOB" in m and "not declared" in m
               for m in msgs)
    assert any("missing from the README environment table" in m
               for m in msgs)


def test_env_flags_bare_getenv_import(tmp_path):
    plant(tmp_path, "reval_tpu/cfg.py", '''from os import getenv

A = getenv("REVAL_TPU_WATCHDOG_S")
''')
    report = run_lint(str(tmp_path), ["env"])
    assert any("raw getenv('REVAL_TPU_WATCHDOG_S')" in m
               for m in messages(report))


def test_unparseable_file_is_reported_not_skipped(tmp_path):
    plant(tmp_path, "reval_tpu/serving/bad.py",
          "def broken(:\n    raise RuntimeError('x')\n")
    report = run_lint(str(tmp_path), ["errors"])
    assert not report.ok
    assert any(v.pass_name == "parse" and "bad.py" in v.path
               for v in report.violations)


def test_locks_annotation_inside_conditional_registers(tmp_path):
    plant(tmp_path, "reval_tpu/locky.py", '''import threading


class Box:
    def __init__(self, cached):
        self._lock = threading.Lock()
        if cached:
            self._cache = {}        # guarded-by: _lock
        else:
            self._cache = None

    def get(self, k):
        with self._lock:
            return self._cache.get(k) if self._cache else None
''')
    report = run_lint(str(tmp_path), ["locks"])
    assert report.ok, messages(report)


def test_env_zombie_check_is_word_boundary(tmp_path):
    """A var whose name prefixes another declared var must still be
    flagged when its only 'reference' is the longer name."""
    from reval_tpu.analysis import envreg
    from reval_tpu.analysis.core import SourceFile

    src = SourceFile("x.py", "reval_tpu/x.py",
                     'A = env_str("REVAL_TPU_LOG_LEVEL")\n')
    fake_env = {"REVAL_TPU_LOG": {}, "REVAL_TPU_LOG_LEVEL": {}}
    out = envreg._check_zombies(str(tmp_path), {"reval_tpu/x.py": src},
                                fake_env)
    flagged = {v.message.split(":")[0] for v in out}
    assert "REVAL_TPU_LOG" in flagged
    assert "REVAL_TPU_LOG_LEVEL" not in flagged


def test_env_registry_runtime_contract(monkeypatch):
    with pytest.raises(KeyError, match="not declared"):
        env_str("REVAL_TPU_TYPO_KNOB")
    monkeypatch.setenv("REVAL_TPU_OBS", "off")
    assert env_flag("REVAL_TPU_OBS", True) is False
    monkeypatch.setenv("REVAL_TPU_OBS", "1")
    assert env_flag("REVAL_TPU_OBS", True) is True
    monkeypatch.setenv("REVAL_TPU_MAX_QUEUED_TOKENS", "")
    assert env_int("REVAL_TPU_MAX_QUEUED_TOKENS", 7) == 7
    monkeypatch.setenv("REVAL_TPU_MAX_QUEUED_TOKENS", "4096")
    assert env_int("REVAL_TPU_MAX_QUEUED_TOKENS", 7) == 4096
    # every declared var documents itself
    for name, spec in ENV.items():
        assert name.startswith("REVAL_TPU_") and spec["help"], name


# ---------------------------------------------------------------------------
# driver plumbing: CLI exit codes, shim compatibility
# ---------------------------------------------------------------------------

def test_cli_exits_nonzero_on_planted_violation(tmp_path):
    plant(tmp_path, "reval_tpu/serving/bad.py",
          'def f():\n    raise RuntimeError("boom")\n')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "reval_lint.py"),
         "--root", str(tmp_path), "errors"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1 and "raise RuntimeError" in r.stdout


def test_cli_lists_all_passes():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "reval_lint.py"),
         "--list"], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    assert set(r.stdout.split()) == set(PASSES)


def test_check_metrics_shim_still_delegates():
    """The historical entry point keeps working (docs/invocations), now
    through the migrated passes."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_metrics.py")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "metrics" in r.stdout and "events" in r.stdout


# ---------------------------------------------------------------------------
# runtime lock sanitizer
# ---------------------------------------------------------------------------

def test_lockcheck_detects_lock_order_inversion():
    san = lockcheck.LockSanitizer()
    a = san.wrap("lock-A")
    b = san.wrap("lock-B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    kinds = [v["kind"] for v in san.violations]
    assert "lock-order-inversion" in kinds
    v = next(v for v in san.violations if v["kind"] == "lock-order-inversion")
    assert {"lock-A", "lock-B"} == {v["a"], v["b"]}


def test_lockcheck_consistent_order_is_clean():
    san = lockcheck.LockSanitizer()
    a, b = san.wrap("A"), san.wrap("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert san.violations == []


def test_lockcheck_inversion_across_threads():
    san = lockcheck.LockSanitizer()
    a, b = san.wrap("A"), san.wrap("B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t = threading.Thread(target=ab)
    t.start()
    t.join()
    ba()
    assert any(v["kind"] == "lock-order-inversion" for v in san.violations)


def test_lockcheck_catches_off_lock_write():
    san = lockcheck.LockSanitizer()

    class Box:
        def __init__(self):
            self._lock = san.wrap("box-lock")
            self._val = 0               # constructor write: exempt

        def bump_locked(self):
            with self._lock:
                self._val += 1

        def bump_racy(self):
            self._val += 1

    undo = lockcheck.audit_class(Box, {"_val": "_lock"}, san)
    try:
        box = Box()
        box.bump_locked()
        assert san.violations == []
        box.bump_racy()
        assert any(v["kind"] == "off-lock-write"
                   and "bump_racy" in v["detail"] for v in san.violations)
    finally:
        undo()


def test_lockcheck_audit_maps_derive_from_annotations():
    """One contract, two enforcement layers: the runtime audit reads the
    SAME ``guarded-by`` comments the static pass does."""
    import reval_tpu.serving.session as session_mod

    maps = lockcheck._module_guard_maps(session_mod)
    assert maps["ContinuousSession"]["_queued_tokens"] == "_acct_lock"
    assert maps["ContinuousSession"]["_inflight"] == "_acct_lock"
    assert maps["_Pending"]["_fired"] == "_cb_lock"
    assert maps["MultiSession"]["_load"] == "_lock"


def test_lockcheck_lock_survives_fork_protocol():
    """concurrent.futures registers _at_fork_reinit on its module lock at
    import; a sanitized lock must speak that protocol or the sanitizer
    breaks `import concurrent.futures` (dp_paged, ThreadPoolExecutor)."""
    san = lockcheck.LockSanitizer()
    lk = san.wrap("forky")
    lk.acquire()
    lk._at_fork_reinit()
    assert not lk.locked() and not lk.held_by_me()


def test_lockcheck_sanitized_lock_speaks_lock_protocol():
    san = lockcheck.LockSanitizer()
    lk = san.wrap("proto")
    assert lk.acquire(False) is True
    assert lk.locked() and lk.held_by_me()
    lk.release()
    assert not lk.locked()
    # a Condition built over it works through the stdlib fallbacks
    cond = threading.Condition(san.wrap("cond-lock"))
    with cond:
        cond.notify_all()


# ---------------------------------------------------------------------------
# typed-error boundary: the fix the pass forced (EngineFailure)
# ---------------------------------------------------------------------------

def test_engine_failure_is_typed_and_wire_unsafe():
    from reval_tpu.serving.errors import EngineFailure, ServingError

    exc = EngineFailure("secret /opt/x token=abc")
    assert isinstance(exc, RuntimeError) and isinstance(exc, ServingError)
    assert exc.status == 500 and exc.code == "internal_error"
    assert exc.wire_safe is False and ServingError.wire_safe is True


def test_server_sanitizes_engine_failure_body():
    import urllib.error
    import urllib.request

    from reval_tpu.serving.errors import EngineFailure
    from reval_tpu.serving.server import EngineServer

    def boom(prompts, *, max_tokens, temperature, stop):
        raise EngineFailure("secret internal path /opt/x token=abc123")

    srv = EngineServer(boom, model_id="m", port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions",
            data=json.dumps({"prompt": "p"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 500
        raw = err.value.read().decode()
        body = json.loads(raw)
        assert body["error"]["code"] == "internal_error"
        assert "secret" not in raw and "token=abc123" not in raw
    finally:
        srv.shutdown()


def test_session_driver_fault_raises_engine_failure():
    """The session's untyped-fault path now crosses the handle typed
    (still a RuntimeError for old callers, message preserved)."""
    from reval_tpu.resilience import EngineStepChaos
    from reval_tpu.serving.errors import EngineFailure
    from reval_tpu.serving.mock_engine import MockStepEngine
    from reval_tpu.serving.session import ContinuousSession

    chaos = EngineStepChaos(rate=1.0, modes=("error",), max_faults=1)
    eng = MockStepEngine()
    session = ContinuousSession(eng, step_chaos=chaos, watchdog_s=0)
    try:
        h = session.submit(["x"], max_new_tokens=8)
        with pytest.raises(EngineFailure, match="chaos"):
            h.result(timeout=30)
    finally:
        session.close()


# ---------------------------------------------------------------------------
# bench: the stale marker (ROADMAP item 5, small slice)
# ---------------------------------------------------------------------------

def test_bench_failure_emits_stale_marker():
    import io
    from contextlib import redirect_stdout

    sys.path.insert(0, REPO)
    import bench

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.fail("m", "tpu-unreachable", "probe timed out")
    out = json.loads(buf.getvalue())
    assert out["error"] == "tpu-unreachable"
    # the repo carries committed clean artifacts, so the wedge round
    # reads as STALE @ last_known instead of a blind 0.0
    assert out["status"] == "stale"
    assert out["stale_probes_per_sec"] == out["last_known"]["value"] > 0
    assert out["stale_commit"] == out["last_known"]["measured_at_commit"]


def test_bench_probe_self_heals_with_retry_backoff():
    """ROADMAP item 5 remainder: the pre-flight tunnel probe retries
    under the resilience layer's RetryPolicy — exponential backoff, not
    a fixed sleep — before a round is ever declared stale, and the
    schedule is unit-testable via the injectable runner/sleep."""
    from types import SimpleNamespace

    sys.path.insert(0, REPO)
    import bench

    calls = {"n": 0}

    def flaky(cmd, **kw):
        # two wedged probes (the transient-tunnel shape), then recovery
        calls["n"] += 1
        if calls["n"] < 3:
            raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 1))
        return SimpleNamespace(returncode=0,
                               stdout="4|TPU v5 lite|tpu\n", stderr="")

    delays: list[float] = []
    health, err = bench.probe_devices(retries=6, wait_s=1.0,
                                      runner=flaky, sleep=delays.append)
    assert health == (4, "TPU v5 lite", "tpu") and err == ""
    assert calls["n"] == 3
    assert len(delays) == 2
    assert delays[1] > delays[0] * 1.5      # backoff grows, no lockstep

    def wedged(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 1))

    delays2: list[float] = []
    health, err = bench.probe_devices(retries=3, wait_s=1.0,
                                      runner=wedged, sleep=delays2.append)
    assert health is None and err == "timeout"  # the stale-marker verdict
    assert len(delays2) == 2                    # bounded budget

    def crashing(cmd, **kw):
        return SimpleNamespace(returncode=1, stdout="", stderr="boom")

    health, err = bench.probe_devices(retries=2, wait_s=0.1, runner=crashing,
                                      sleep=lambda s: None)
    assert health is None and "rc=1" in err and "boom" in err


# ---------------------------------------------------------------------------
# jit pass bites (ISSUE 9)
# ---------------------------------------------------------------------------

JITTY = '''import jax


def compute(x, steps):
    if steps > 2:               # steps is static: a Python value
        return x * 2.0
    return x + steps


# jit-entry: toy.compute static=(steps) bucketed=(rows) warmup=4
fn = jax.jit(compute, static_argnames=("steps",))
'''


def test_jit_clean_annotated_site_passes(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", JITTY)
    report = run_lint(str(tmp_path), ["jit"])
    assert report.ok, messages(report)


def test_jit_flags_undeclared_site(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", '''import jax

fn = jax.jit(lambda x: x * 2)
''')
    report = run_lint(str(tmp_path), ["jit"])
    assert any("undeclared jit entry point" in m for m in messages(report))


def test_jit_out_of_scope_dirs_uncovered(tmp_path):
    # the serving layer may jit freely — only the compiled core declares
    plant(tmp_path, "reval_tpu/serving/toy.py", '''import jax

fn = jax.jit(lambda x: x * 2)
''')
    report = run_lint(str(tmp_path), ["jit"])
    assert report.ok, messages(report)


def test_jit_flags_traced_value_branch(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", JITTY.replace(
        "if steps > 2:               # steps is static: a Python value",
        "if x > 2:"))
    report = run_lint(str(tmp_path), ["jit"])
    assert any("traced parameter(s) x" in m for m in messages(report))


def test_jit_is_none_structural_branch_exempt(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", '''import jax


def compute(x, mask):
    if mask is not None:        # structure, not data: retrace contract
        return x * mask
    return x


# jit-entry: toy.compute bucketed=(rows)
fn = jax.jit(compute)
''')
    report = run_lint(str(tmp_path), ["jit"])
    assert report.ok, messages(report)


def test_jit_guard_then_compare_still_bites(tmp_path):
    # the `is not None` clause exempts only ITS OWN occurrence of x —
    # the data-dependent `x > 2` in the same test must still flag
    plant(tmp_path, "reval_tpu/models/toy.py", '''import jax


def compute(x, mask):
    if mask is not None and mask > 2:
        return x * mask
    return x


# jit-entry: toy.guarded bucketed=(rows)
fn = jax.jit(compute)
''')
    report = run_lint(str(tmp_path), ["jit"])
    assert any("traced parameter(s) mask" in m for m in messages(report))


def test_jit_static_round_trip_bites_both_directions(tmp_path):
    # annotation promises FEWER statics than the call declares
    plant(tmp_path, "reval_tpu/models/toy.py", JITTY.replace(
        "static=(steps) ", ""))
    report = run_lint(str(tmp_path), ["jit"])
    assert any("does not match the call's static_argnames" in m
               for m in messages(report))
    # annotation promises MORE statics than the call declares
    plant(tmp_path, "reval_tpu/models/toy.py", JITTY.replace(
        'fn = jax.jit(compute, static_argnames=("steps",))',
        'fn = jax.jit(compute)'))
    report = run_lint(str(tmp_path), ["jit"])
    assert any("no static_argnames" in m for m in messages(report))


def test_jit_bans_static_argnums(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", JITTY.replace(
        'static_argnames=("steps",)', "static_argnums=(1,)"))
    report = run_lint(str(tmp_path), ["jit"])
    assert any("static_argnums" in m and "silently go stale" in m
               for m in messages(report))


def test_jit_bans_computed_static_argnames(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py",
          "NAMES = (\"steps\",)\n" + JITTY.replace(
              'static_argnames=("steps",)', "static_argnames=NAMES"))
    report = run_lint(str(tmp_path), ["jit"])
    assert any("not a string literal" in m for m in messages(report))


def test_jit_duplicate_shape_key(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", JITTY + '''

# jit-entry: toy.compute static=(steps) bucketed=(rows) warmup=4
fn2 = jax.jit(compute, static_argnames=("steps",))
''')
    report = run_lint(str(tmp_path), ["jit"])
    assert any("duplicate jit-entry shape-key" in m for m in messages(report))


def test_jit_tracked_jit_literals_cross_checked(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", '''import jax

from reval_tpu.analysis.jitcheck import tracked_jit


def compute(x):
    return x * 2.0


# jit-entry: toy.compute warmup=4
fn = tracked_jit("toy.other", jax.jit(compute), warmup=3)
''')
    report = run_lint(str(tmp_path), ["jit"])
    msgs = messages(report)
    assert any("tracked_jit name 'toy.other'" in m for m in msgs)
    assert any("warmup=3 does not match" in m for m in msgs)


def test_jit_unparseable_annotation_tail_reported(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", JITTY.replace(
        "warmup=4", "warmup=soon"))
    report = run_lint(str(tmp_path), ["jit"])
    assert any("unparseable tail" in m for m in messages(report))


# ---------------------------------------------------------------------------
# hostsync pass bites (ISSUE 9)
# ---------------------------------------------------------------------------

def test_hostsync_flags_transfer_in_hot_path(tmp_path):
    plant(tmp_path, "reval_tpu/eng.py", '''import numpy as np


def tick(state):   # hot-path
    toks = np.asarray(state.tokens)
    return toks.tolist()
''')
    report = run_lint(str(tmp_path), ["hostsync"])
    msgs = messages(report)
    assert any("np.asarray" in m for m in msgs)
    assert any("toks.tolist" in m for m in msgs)


def test_hostsync_reasoned_suppression_passes(tmp_path):
    plant(tmp_path, "reval_tpu/eng.py", '''import numpy as np


def tick(state):   # hot-path
    # host-sync: the chunk's one deliberate ground-truth fetch
    return np.asarray(state.tokens)
''')
    report = run_lint(str(tmp_path), ["hostsync"])
    assert report.ok, messages(report)


def test_hostsync_bare_marker_is_itself_a_violation(tmp_path):
    plant(tmp_path, "reval_tpu/eng.py", '''import numpy as np


def tick(state):   # hot-path
    # host-sync:
    return np.asarray(state.tokens)
''')
    report = run_lint(str(tmp_path), ["hostsync"])
    msgs = messages(report)
    assert any("without a reason" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)   # nothing was silenced


def test_hostsync_flags_tracer_concretization_in_jit_body(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", '''import jax


def compute(x, n):
    return x * float(n)


# jit-entry: toy.compute bucketed=(rows)
fn = jax.jit(compute)
''')
    report = run_lint(str(tmp_path), ["hostsync"])
    assert any("float() on traced parameter(s) n" in m
               for m in messages(report))


def test_hostsync_static_param_concretization_is_fine(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", '''import jax


def compute(x, n):
    return x * float(n)        # n is static: a Python value here


# jit-entry: toy.compute static=(n) bucketed=(rows)
fn = jax.jit(compute, static_argnames=("n",))
''')
    report = run_lint(str(tmp_path), ["hostsync"])
    assert report.ok, messages(report)


def test_hostsync_flags_device_get_in_jit_body(tmp_path):
    plant(tmp_path, "reval_tpu/models/toy.py", '''import jax


def compute(x):
    return jax.device_get(x)


# jit-entry: toy.compute bucketed=(rows)
fn = jax.jit(compute)
''')
    report = run_lint(str(tmp_path), ["hostsync"])
    assert any("jax.device_get" in m for m in messages(report))


# ---------------------------------------------------------------------------
# tilecontract pass bites (ISSUE 9)
# ---------------------------------------------------------------------------

def test_tile_missing_contract_bites(tmp_path):
    plant(tmp_path, "reval_tpu/ops/kern.py", '''from jax.experimental import pallas as pl


def run(q, kernel):
    return pl.pallas_call(kernel, out_shape=q)(q)
''')
    report = run_lint(str(tmp_path), ["tilecontract"])
    assert any("without a '# tile:" in m for m in messages(report))


def test_tile_misaligned_minor_dim_bites(tmp_path):
    plant(tmp_path, "reval_tpu/ops/kern.py", '''from jax.experimental import pallas as pl


def run(q, kernel):
    # tile: (8, 128)
    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
        out_shape=q,
    )(q)
''')
    report = run_lint(str(tmp_path), ["tilecontract"])
    assert any("minor dim 100" in m and "128" in m for m in messages(report))


def test_tile_misaligned_second_minor_bites(tmp_path):
    plant(tmp_path, "reval_tpu/ops/kern.py", '''from jax.experimental import pallas as pl


def run(q, kernel):
    # tile: (8, 128)
    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((12, 256), lambda i: (i, 0))],
        out_shape=q,
    )(q)
''')
    report = run_lint(str(tmp_path), ["tilecontract"])
    assert any("second-minor dim 12" in m for m in messages(report))


def test_tile_illegal_declared_tile_bites(tmp_path):
    plant(tmp_path, "reval_tpu/ops/kern.py", '''from jax.experimental import pallas as pl


def run(q, kernel):
    # tile: (5, 128)
    return pl.pallas_call(kernel, out_shape=q)(q)
''')
    report = run_lint(str(tmp_path), ["tilecontract"])
    assert any("sublane tile 5" in m for m in messages(report))


def test_tile_clean_kernel_with_symbolic_dims_passes(tmp_path):
    plant(tmp_path, "reval_tpu/ops/kern.py", '''from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import jax.numpy as jnp

LANES = 256


def run(q, kernel, h, d):
    # tile: (8, 128)
    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
                  pl.BlockSpec((8, LANES), lambda i: (i, 0))],
        scratch_shapes=[pltpu.VMEM((h, 128), jnp.float32)],
        out_shape=q,
    )(q)
''')
    report = run_lint(str(tmp_path), ["tilecontract"])
    assert report.ok, messages(report)


def test_tile_suppression_with_reason_is_counted(tmp_path):
    plant(tmp_path, "reval_tpu/ops/kern.py", '''from jax.experimental import pallas as pl


def run(q, kernel):
    # tile: (8, 128)
    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],  # lint: allow(tilecontract) — deliberately sub-tile scalar row, padding measured acceptable
        out_shape=q,
    )(q)
''')
    report = run_lint(str(tmp_path), ["tilecontract"])
    assert report.ok, messages(report)
    assert len(report.suppressions) == 1
    assert "sub-tile" in report.suppressions[0].reason


# ---------------------------------------------------------------------------
# mesh pass bites (ISSUE 11)
# ---------------------------------------------------------------------------

MINI_AXES = '''AXES = {
    "dp": "data parallel",
    "pp": "pipeline parallel",
    "sp": "sequence parallel",
    "ep": "expert parallel",
    "tp": "tensor parallel",
}
'''


def plant_axes(tmp_path):
    plant(tmp_path, "reval_tpu/parallel/mesh.py", MINI_AXES)


def test_mesh_flags_undeclared_constructor(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/shards.py",
          '''from jax.sharding import PartitionSpec as P

SPEC = P("dp")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("without a '# mesh:" in m for m in messages(report))


def test_mesh_clean_contract_passes(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/shards.py",
          '''from jax.sharding import PartitionSpec as P

# mesh: axes=(dp)
SPEC = P("dp")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert report.ok, messages(report)


def test_mesh_flags_unregistered_axis(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/shards.py",
          '''from jax.sharding import PartitionSpec as P

# mesh: axes=(zz)
SPEC = P("zz")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("not registered" in m for m in messages(report))


def test_mesh_flags_typoed_literal_axis(tmp_path):
    # the headline failure mode: "ttp" would surface as a runtime XLA
    # unbound-axis error deep inside a trace; here it is a lint line
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/shards.py",
          '''from jax.sharding import PartitionSpec as P

# mesh: axes=(dp, tp)
SPEC = P("ttp")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("'ttp'" in m and "not declared" in m
               for m in messages(report))


def test_mesh_missing_axes_registry_is_reported(tmp_path):
    plant(tmp_path, "reval_tpu/parallel/shards.py",
          '''from jax.sharding import PartitionSpec as P

# mesh: axes=(dp)
SPEC = P("dp")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("AXES registry" in m for m in messages(report))


def test_mesh_shard_map_requires_in_out(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/sm.py",
          '''import jax
from jax.sharding import PartitionSpec as P


def f(m, fn, x):
    # mesh: axes=(dp)
    return jax.shard_map(fn, mesh=m, in_specs=(P("dp"),),
                         out_specs=P("dp"))(x)
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("must declare in=" in m for m in messages(report))
    assert any("must declare out=" in m for m in messages(report))


def test_mesh_shard_map_spec_roundtrip_mismatch(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/sm.py",
          '''import jax
from jax.sharding import PartitionSpec as P


def f(m, fn, x):
    # mesh: axes=(dp, tp) in=(P(dp)) out=(P(dp))
    return jax.shard_map(fn, mesh=m, in_specs=(P("tp"),),
                         out_specs=P("dp"))(x)
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("does not round-trip" in m for m in messages(report))


def test_mesh_shard_map_literal_roundtrip_clean(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/sm.py",
          '''import jax
from jax.sharding import PartitionSpec as P


def f(m, fn, x):
    # mesh: axes=(dp, tp) in=(P(dp), P(None, tp)) out=(P(dp))
    return jax.shard_map(fn, mesh=m,
                         in_specs=(P("dp"), P(None, "tp")),
                         out_specs=P("dp"))(x)
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert report.ok, messages(report)


def test_mesh_dynamic_annotation_over_literal_specs_flagged(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/sm.py",
          '''import jax
from jax.sharding import PartitionSpec as P


def f(m, fn, x):
    # mesh: axes=(dp) in=(dynamic) out=(dynamic)
    return jax.shard_map(fn, mesh=m, in_specs=(P("dp"),),
                         out_specs=P("dp"))(x)
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("declare the specs so they are checked" in m
               for m in messages(report))


def test_mesh_collective_outside_contract_flagged(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/coll.py",
          '''from jax import lax


def reduce_it(x):
    return lax.psum(x, "dp")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("outside any '# mesh:' contract" in m
               for m in messages(report))


def test_mesh_collective_axis_outside_contract_flagged(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/coll.py",
          '''from jax import lax


# mesh: axes=(tp)
def reduce_it(x):
    return lax.psum(x, "dp")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("outside the contract's axes" in m for m in messages(report))


def test_mesh_collective_via_parameter(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/coll.py",
          '''from jax import lax


# mesh: axes=(sp) via=(axis_name)
def ok(x, axis_name):
    return lax.ppermute(x, axis_name, [(0, 1)])


# mesh: axes=(sp)
def bad(x, axis_name):
    return lax.ppermute(x, axis_name, [(0, 1)])
''')
    report = run_lint(str(tmp_path), ["mesh"])
    flagged = messages(report, "mesh")
    assert len(flagged) == 1
    assert "via=" in flagged[0]


# ---------------------------------------------------------------------------
# reshard pass bites
# ---------------------------------------------------------------------------

def test_reshard_constraint_needs_reason(tmp_path):
    plant(tmp_path, "reval_tpu/parallel/sp.py",
          '''import jax


def constrain(h, s):
    return jax.lax.with_sharding_constraint(h, s)
''')
    report = run_lint(str(tmp_path), ["reshard"])
    assert any("with_sharding_constraint" in m for m in messages(report))


def test_reshard_reasoned_constraint_clean(tmp_path):
    plant(tmp_path, "reval_tpu/parallel/sp.py",
          '''import jax


def constrain(h, s):
    # reshard: keep activations sequence-sharded through the norms
    return jax.lax.with_sharding_constraint(h, s)
''')
    report = run_lint(str(tmp_path), ["reshard"])
    assert report.ok, messages(report)


def test_reshard_bare_marker_reports(tmp_path):
    plant(tmp_path, "reval_tpu/parallel/sp.py",
          '''import jax


def constrain(h, s):
    # reshard:
    return jax.lax.with_sharding_constraint(h, s)
''')
    report = run_lint(str(tmp_path), ["reshard"])
    assert any("without a reason" in m for m in messages(report))


def test_reshard_device_put_in_hot_path_needs_reason(tmp_path):
    plant(tmp_path, "reval_tpu/parallel/hot.py",
          '''import jax


class Engine:
    def _drive_tick(self, x, s):   # hot-path
        return jax.device_put(x, s)
''')
    report = run_lint(str(tmp_path), ["reshard"])
    assert any("device_put" in m for m in messages(report))
    plant(tmp_path, "reval_tpu/parallel/hot.py",
          '''import jax


class Engine:
    def _drive_tick(self, x, s):   # hot-path
        # reshard: tokens must land dp-sharded before the chunk dispatch
        return jax.device_put(x, s)
''')
    report = run_lint(str(tmp_path), ["reshard"])
    assert report.ok, messages(report)


def test_reshard_full_replication_in_hot_path_flagged(tmp_path):
    plant(tmp_path, "reval_tpu/parallel/hot.py",
          '''from jax.sharding import PartitionSpec


class Engine:
    def _drive_tick(self, x):   # hot-path
        return PartitionSpec()
''')
    report = run_lint(str(tmp_path), ["reshard"])
    assert any("full replication" in m for m in messages(report))


# ---------------------------------------------------------------------------
# zombie-suppression detection (driver/core)
# ---------------------------------------------------------------------------

def test_zombie_suppression_flagged(tmp_path):
    # an allow whose pass ran and found NOTHING at that site excused
    # code that is gone — the waiver must die with it
    plant(tmp_path, "reval_tpu/clean.py", '''import time


def slow():   # not hot-path: nothing here violates anything
    # lint: allow(hotpath) — this sleep used to sit on the drive tick
    time.sleep(0.1)
''')
    report = run_lint(str(tmp_path), ["hotpath"])
    assert any("zombie suppression" in m for m in messages(report))


def test_zombie_not_flagged_when_pass_not_run(tmp_path):
    plant(tmp_path, "reval_tpu/clean.py", '''import time


def slow():
    # lint: allow(hotpath) — this sleep used to sit on the drive tick
    time.sleep(0.1)
''')
    report = run_lint(str(tmp_path), ["locks"])
    assert not any("zombie" in m for m in messages(report))


def test_used_suppression_not_zombie(tmp_path):
    plant(tmp_path, "reval_tpu/hot.py", '''import time


class E:
    def _tick(self):   # hot-path
        # lint: allow(hotpath) — deliberate pacing knob for tests
        time.sleep(0.01)
''')
    report = run_lint(str(tmp_path), ["hotpath"])
    assert report.ok, messages(report)
    assert len(report.suppressions) == 1
    assert not any("zombie" in m for m in messages(report))


def test_allow_naming_unknown_pass_flagged(tmp_path):
    plant(tmp_path, "reval_tpu/clean.py", '''X = 1
# lint: allow(hotpth) — typo'd pass name silently never matches
Y = 2
''')
    report = run_lint(str(tmp_path), ["locks"])
    assert any("unknown pass 'hotpth'" in m for m in messages(report))


# ---------------------------------------------------------------------------
# enginezoo pass bites
# ---------------------------------------------------------------------------

def _real_sources():
    from reval_tpu.analysis.core import collect_sources

    return collect_sources(REPO)


def _mutated(sources, rel, old, new):
    from reval_tpu.analysis.core import SourceFile

    src = sources[rel]
    assert old in src.text, f"fixture drift: {old!r} not in {rel}"
    out = dict(sources)
    out[rel] = SourceFile(src.path, rel, src.text.replace(old, new))
    return out


def test_enginezoo_repo_matrix_is_complete():
    """The committed artifact lists every engine × surface member as
    implemented/delegated/not-supported-with-reason."""
    from reval_tpu.analysis.enginezoo import ENGINES, SURFACE

    with open(os.path.join(REPO, "ENGINE_SURFACE.md")) as f:
        rows = [l for l in f.read().splitlines()
                if l.startswith("| `")]
    assert len(rows) == len(SURFACE)
    for row in rows:
        cells = [c.strip() for c in row.split("|")[2:-1]]
        assert len(cells) == len(ENGINES)
        for cell in cells:
            assert (cell == "yes" or cell.startswith("->")
                    or cell.startswith("NO: ")), f"bad cell {cell!r} in {row}"


def test_enginezoo_orphan_method_bites(tmp_path):
    from reval_tpu.analysis import enginezoo

    sources = _mutated(
        _real_sources(), "reval_tpu/serving/mock_engine.py",
        "    def close(self) -> None:",
        "    def brand_new_feature(self):\n"
        "        return 1\n\n"
        "    def close(self) -> None:")
    out = enginezoo.run(sources, REPO)
    assert any("orphan engine method MockStepEngine.brand_new_feature"
               in v.message for v in out)


def test_enginezoo_engine_local_marker_accepted(tmp_path):
    from reval_tpu.analysis import enginezoo

    sources = _mutated(
        _real_sources(), "reval_tpu/serving/mock_engine.py",
        "    def close(self) -> None:",
        "    # engine-local: mock-only chaos knob, not an engine feature\n"
        "    def brand_new_feature(self):\n"
        "        return 1\n\n"
        "    def close(self) -> None:")
    out = enginezoo.run(sources, REPO)
    assert not any("orphan" in v.message for v in out)
    # (the artifact check still fires nothing: engine-local methods are
    # not part of the matrix)
    assert not any("stale" in v.message for v in out)


def test_enginezoo_missing_member_bites():
    from reval_tpu.analysis import enginezoo

    sources = _mutated(
        _real_sources(), "reval_tpu/inference/tpu/engine.py",
        "    # not-supported: close — no driver thread or pool; "
        "generate() leaves nothing running\n", "")
    out = enginezoo.run(sources, REPO)
    assert any("neither implements, inherits, nor declares" in v.message
               and "'close'" in v.message for v in out)


def test_enginezoo_zombie_not_supported_marker_bites():
    from reval_tpu.analysis import enginezoo

    sources = _mutated(
        _real_sources(), "reval_tpu/inference/tpu/paged_engine.py",
        "class PagedTPUEngine:",
        "class PagedTPUEngine:\n"
        "    # not-supported: generate — stale claim, it IS implemented")
    out = enginezoo.run(sources, REPO)
    assert any("zombie not-supported marker" in v.message for v in out)


def test_enginezoo_stale_artifact_bites(tmp_path):
    from reval_tpu.analysis import enginezoo

    with open(os.path.join(REPO, "ENGINE_SURFACE.md")) as f:
        doc = f.read()
    plant(tmp_path, "ENGINE_SURFACE.md", doc.replace("yes", "maybe", 1))
    out = enginezoo.run(_real_sources(), str(tmp_path))
    assert any("stale" in v.message for v in out)


def test_enginezoo_reasonless_marker_bites():
    from reval_tpu.analysis import enginezoo

    sources = _mutated(
        _real_sources(), "reval_tpu/inference/tpu/dp_paged.py",
        "    # not-supported: release_request — replicas own request teardown",
        "    # not-supported: release_request")
    out = enginezoo.run(sources, REPO)
    assert any("without a reason" in v.message for v in out)


# ---------------------------------------------------------------------------
# CLI: --json, --changed-only, exit codes (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def lint_cli(*args, cwd=None):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "reval_lint.py"),
         *args], capture_output=True, text=True, cwd=cwd or REPO)


def test_cli_json_clean_tree(tmp_path):
    plant(tmp_path, "reval_tpu/ok.py", "X = 1\n")
    proc = lint_cli("--json", "locks", "hotpath", "--root", str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    assert set(doc["passes"]) == {"locks", "hotpath"}
    for info in doc["passes"].values():
        assert info["violations"] == 0
        assert isinstance(info["elapsed_s"], float)


def test_cli_json_violations_and_exit_code(tmp_path):
    plant(tmp_path, "reval_tpu/bad.py", '''import time


class E:
    def _tick(self):   # hot-path
        time.sleep(1)
''')
    proc = lint_cli("--json", "hotpath", "--root", str(tmp_path))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["ok"] is False
    assert doc["passes"]["hotpath"]["violations"] == 1
    v = doc["violations"][0]
    assert v["pass"] == "hotpath" and v["path"].endswith("bad.py")
    assert v["line"] == 6


def test_cli_unknown_pass_exit_2():
    proc = lint_cli("nonsense")
    assert proc.returncode == 2
    assert "unknown lint pass" in proc.stdout


def test_cli_changed_only_outside_git_exit_2(tmp_path):
    plant(tmp_path, "reval_tpu/ok.py", "X = 1\n")
    proc = lint_cli("--changed-only", "locks", "--root", str(tmp_path))
    assert proc.returncode == 2
    assert "git" in proc.stdout


BAD_HOT = '''import time


class E:
    def _tick(self):   # hot-path
        time.sleep(1)
'''


def test_cli_changed_only_scopes_to_git_diff(tmp_path):
    # committed violating file + untracked violating file: the scoped
    # run reports ONLY the untracked one; the full run reports both
    plant(tmp_path, "reval_tpu/committed.py", BAD_HOT)
    git = ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
           "-c", "user.name=t"]
    subprocess.run(git[:3] + ["init", "-q"], check=True)
    subprocess.run(git + ["add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "seed"], check=True)
    plant(tmp_path, "reval_tpu/fresh.py", BAD_HOT)

    full = lint_cli("--json", "hotpath", "--root", str(tmp_path))
    assert json.loads(full.stdout)["passes"]["hotpath"]["violations"] == 2

    scoped = lint_cli("--json", "--changed-only", "hotpath",
                      "--root", str(tmp_path), cwd=str(tmp_path))
    assert scoped.returncode == 1
    doc = json.loads(scoped.stdout)
    assert doc["passes"]["hotpath"]["violations"] == 1
    assert doc["violations"][0]["path"].endswith("fresh.py")


def test_fifteen_passes_registered():
    assert len(PASSES) == 15
    assert {"mesh", "reshard", "enginezoo", "kernelbench",
            "goldenstreams"} <= set(PASSES)


def test_mesh_collective_via_lax_import_alias(tmp_path):
    # `from jax.lax import psum` must not bypass the pass
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/coll2.py",
          '''from jax.lax import psum


def reduce_it(x):
    return psum(x, "dp")
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("outside any '# mesh:' contract" in m
               for m in messages(report))


def test_mesh_walks_match_case_bodies(tmp_path):
    plant_axes(tmp_path)
    plant(tmp_path, "reval_tpu/parallel/matchy.py",
          '''from jax.sharding import PartitionSpec as P


def pick(kind):
    match kind:
        case "a":
            return P("ttp")
        case _:
            return P()
''')
    report = run_lint(str(tmp_path), ["mesh"])
    assert any("without a '# mesh:" in m for m in messages(report))


def test_reshard_bare_marker_reports_exactly_once(tmp_path):
    # one defect, one violation — never a second 'marker missing'
    # report at the call site pointing the fix the wrong way
    plant(tmp_path, "reval_tpu/parallel/sp.py",
          '''import jax


def constrain(h, s):
    # reshard:
    return jax.lax.with_sharding_constraint(h, s)
''')
    report = run_lint(str(tmp_path), ["reshard"])
    assert len(messages(report, "reshard")) == 1
    assert "without a reason" in messages(report, "reshard")[0]
