"""Reproducibility receipts: provenance on every response, end to end.

Unit layer: the ``reval-receipt-v1`` canonical form (obs/receipts.py)
round-trips, refuses garbage, and its digests certify exactly the id
streams they were built from.

Serving layer (host-only: mock engines behind the real session/server
stack over real HTTP): the receipt rides the ``X-Reval-Receipt`` header,
the JSON ``receipt`` field, and the SSE ``reval.receipt`` trailer; a
mid-stream client disconnect neither crashes the server nor corrupts
the next request's receipt.

Fleet layer: two identical mock replicas fingerprint byte-identically
and digest byte-identically for the same prompt; after a failover the
receipt names the replica that ACTUALLY served.  The skew drill flips
``REVAL_TPU_KERNEL_DOT`` on one replica: the router's health poll sees
two fingerprints, fires the edge-triggered ``router.fingerprint_skew``
event + ``reval_receipt_skew_total`` counter, and a pinned tenant sheds
typed-429 instead of landing on the divergent replica.

Golden-stream gate: ``golden_doc``/``validate_golden``/``golden_gate``
(obs/determinism.py) on synthetic matrices, the committed
``GOLDEN_STREAMS.json`` validating at HEAD, and the ``goldenstreams``
lint pass refusing a corrupted registry.
"""

from __future__ import annotations

import copy
import json
import os
import time
import urllib.error
import urllib.request

import pytest

from reval_tpu.inference.client import HTTPClientBackend
from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
from reval_tpu.obs import metrics as obs_metrics
from reval_tpu.obs.metrics import parse_prometheus
from reval_tpu.obs.receipts import (SCHEMA, build_receipt,
                                    digest_matches_ids, digest_matches_text,
                                    encode_receipt, fold_digests,
                                    parse_receipt, token_digest,
                                    validate_receipt)
from reval_tpu.serving import FleetRouter, serve_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# units — the canonical form
# ---------------------------------------------------------------------------

class TestReceiptUnits:
    def test_token_digest_is_an_id_function_not_a_text_function(self):
        assert token_digest([1, 2, 3]) == token_digest([1, 2, 3])
        assert token_digest([1, 2, 3]) != token_digest([1, 2, 4])
        assert token_digest([1, 2, 3]) != token_digest([1, 2])
        # an EOS/padding id flip text rendering cannot show still moves it
        assert token_digest([65, 257]) != token_digest([65])
        assert len(token_digest([7])) == 16

    def test_fold_is_order_sensitive(self):
        a, b = token_digest([1]), token_digest([2])
        assert fold_digests([a, b]) != fold_digests([b, a])

    def test_build_encode_parse_roundtrip(self):
        r = build_receipt("f" * 64, "pid-abc", [token_digest([1, 2])], 2,
                          grammar="yesno", sampling={"temperature": 0.0})
        assert validate_receipt(r) == []
        back = parse_receipt(encode_receipt(r))
        assert back == r
        assert back["schema"] == SCHEMA

    def test_parse_refuses_garbage_and_unknown_schema(self):
        with pytest.raises(ValueError):
            parse_receipt("not json {")
        bad = build_receipt("f", "e", [], 0)
        bad["schema"] = "reval-receipt-v999"
        with pytest.raises(ValueError):
            parse_receipt(encode_receipt(bad))

    def test_validate_catches_a_digest_that_does_not_fold(self):
        r = build_receipt("f", "e", [token_digest([1])], 1)
        r["digest"] = "0" * 16
        assert any("fold" in e for e in validate_receipt(r))

    def test_digest_matches_ids_and_text(self):
        tok = ByteTokenizer()
        text = "YES"
        ids = [t for t in tok.encode(text) if t != tok.bos_id]
        r = build_receipt("f", "e", [token_digest(ids + [tok.eos_id])],
                          len(ids) + 1)
        assert digest_matches_ids(r, [ids + [tok.eos_id]])
        assert not digest_matches_ids(r, [ids + [tok.eos_id, 1]])
        # text path accepts the stream with-or-without the trailing EOS
        assert digest_matches_text(r, [text], tok)
        assert not digest_matches_text(r, ["NO"], tok)
        assert not digest_matches_text(r, [text, "extra"], tok)


# ---------------------------------------------------------------------------
# one mock server — header, body, SSE trailer, disconnect
# ---------------------------------------------------------------------------

def _post(port, body, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def _statusz(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/statusz",
                                timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture
def mock_server():
    server = serve_config({"mock": True, "mock_echo": True}, port=0).start()
    yield server
    server.shutdown()


class TestMockServerReceipts:
    def test_header_and_body_carry_the_same_valid_receipt(self, mock_server):
        body, headers = _post(mock_server.port,
                              {"prompt": ["alpha", "beta"], "max_tokens": 32})
        receipt = body["receipt"]
        assert validate_receipt(receipt) == []
        assert parse_receipt(headers["X-Reval-Receipt"]) == receipt
        assert len(receipt["digests"]) == 2     # one per prompt, in order
        # the fingerprint is the engine-level one readiness advertises
        ready = _statusz(mock_server.port)["readiness"]
        assert receipt["fingerprint"] == ready["fingerprint"]
        assert receipt["engine_id"] == ready["engine_id"]
        # the mock tokenizer round-trips exactly: the digest certifies
        # the returned texts
        tok = mock_server._session.engine.tokenizer
        texts = [c["text"] for c in body["choices"]]
        assert digest_matches_text(receipt, texts, tok)

    def test_client_backend_captures_and_verifies_the_receipt(
            self, mock_server):
        client = HTTPClientBackend(model_id="m", port=mock_server.port,
                                   temp=0.0, prompt_type="direct")
        client.infer_one("receipt probe")
        assert client.last_receipt is not None
        assert validate_receipt(client.last_receipt) == []
        assert len(client.receipt_fingerprints) == 1

    def test_sse_trailer_rides_before_done(self, mock_server):
        req = urllib.request.Request(
            f"http://127.0.0.1:{mock_server.port}/v1/completions",
            data=json.dumps({"prompt": "stream me", "max_tokens": 16,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        events = []
        with urllib.request.urlopen(req, timeout=30) as resp:
            for raw in resp:
                raw = raw.decode().strip()
                if raw.startswith("data: "):
                    events.append(raw[len("data: "):])
        assert events[-1] == "[DONE]"
        trailer = json.loads(events[-2])
        assert trailer["object"] == "reval.receipt"
        receipt = trailer["receipt"]
        assert validate_receipt(receipt) == []
        # the trailer certifies the assembled stream text
        text = "".join(json.loads(e)["choices"][0]["text"]
                       for e in events[:-2]
                       if json.loads(e).get("object") == "text_completion")
        tok = mock_server._session.engine.tokenizer
        assert digest_matches_text(receipt, [text], tok)

    def test_mid_stream_disconnect_leaves_the_server_receipting(self):
        # slow mock steps so the disconnect lands mid-generation
        server = serve_config({"mock": True, "mock_echo": True,
                               "mock_step_s": 0.02}, port=0).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/completions",
                data=json.dumps({"prompt": "doomed stream",
                                 "max_tokens": 64,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            resp = urllib.request.urlopen(req, timeout=30)
            first = resp.readline()             # at least one delta arrived
            assert first.startswith(b"data:")
            resp.close()                        # hang up mid-stream
            # the worker finishes server-side; the next request's receipt
            # must be intact — a torn socket must not corrupt provenance
            time.sleep(0.1)
            body, headers = _post(server.port, {"prompt": "survivor",
                                                "max_tokens": 16})
            receipt = body["receipt"]
            assert validate_receipt(receipt) == []
            assert parse_receipt(headers["X-Reval-Receipt"]) == receipt
            assert _statusz(server.port)["readiness"]["ready"]
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# fleet — provenance under failover, fingerprint convergence + skew
# ---------------------------------------------------------------------------

def make_replica(**cfg):
    base = {"mock": True, "mock_echo": True}
    base.update(cfg)
    return serve_config(base, port=0).start()


def make_router(servers, **kw):
    kw.setdefault("health_interval_s", 0.05)
    kw.setdefault("cooldown_s", 0.4)
    kw.setdefault("eject_fails", 2)
    router = FleetRouter([f"127.0.0.1:{s.port}" for s in servers],
                         port=0, **kw)
    return router.start()


def wait_for(predicate, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def hard_kill(server) -> None:
    server._httpd.shutdown()
    server._httpd.server_close()


def post_router(router, prompt, max_tokens=32, extra=None):
    body = {"prompt": prompt, "max_tokens": max_tokens}
    body.update(extra or {})
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def prompt_targeting(router, replica_id) -> str:
    from reval_tpu.serving.router import affinity_key

    window = router.window_chars
    for i in range(4096):
        p = f"targeted receipt template {i} | " + "pad | " * 40
        if router._ring.order(affinity_key(p, window))[0] == replica_id:
            return p
    raise AssertionError(f"no prompt hashes to {replica_id}")


class TestFleetReceipts:
    def test_identical_configs_fingerprint_and_digest_identically(self):
        a, b = make_replica(), make_replica()
        try:
            body_a, _ = _post(a.port, {"prompt": "same prompt",
                                       "max_tokens": 16})
            body_b, _ = _post(b.port, {"prompt": "same prompt",
                                       "max_tokens": 16})
            ra, rb = body_a["receipt"], body_b["receipt"]
            # byte-identical configs → byte-identical fingerprints, and
            # (echo mode: tokens are a function of the prompt alone)
            # byte-identical digests — but distinct engine identities
            assert ra["fingerprint"] == rb["fingerprint"]
            assert ra["digest"] == rb["digest"]
            assert ra["engine_id"] != rb["engine_id"]
        finally:
            a.shutdown()
            b.shutdown()

    def test_failover_receipt_names_the_replica_that_served(self):
        a, b = make_replica(), make_replica()
        router = make_router([a, b])
        try:
            wait_for(lambda: router.readiness()["ready"], what="router ready")
            ids = {s: _statusz(s.port)["readiness"]["engine_id"]
                   for s in (a, b)}
            prompt = prompt_targeting(router, f"127.0.0.1:{a.port}")
            served = post_router(router, prompt)["receipt"]
            assert served["engine_id"] == ids[a]
            hard_kill(a)
            # same prompt, same ring primary — the forward fails over and
            # the receipt must name the SURVIVOR, not the ring primary
            failed_over = post_router(router, prompt)["receipt"]
            assert failed_over["engine_id"] == ids[b]
            assert failed_over["fingerprint"] == served["fingerprint"]
        finally:
            router.shutdown()
            b.shutdown()

    def test_skew_drill_event_metric_and_pinned_tenant_shed(
            self, monkeypatch):
        good = make_replica()
        # the divergent replica: a different trace-time kernel knob,
        # snapshotted into the engine's receipt context at construction
        monkeypatch.setenv("REVAL_TPU_KERNEL_DOT", "dot")
        bad = make_replica()
        monkeypatch.delenv("REVAL_TPU_KERNEL_DOT")
        router = make_router([good, bad], pin_tenants=["alpha"])
        try:
            wait_for(lambda: router.readiness()["ready"], what="router ready")
            wait_for(lambda: len(router.statusz()["fingerprints"]) == 2,
                     what="both fingerprints polled")
            fps = router.statusz()["fingerprints"]
            assert sorted(len(v) for v in fps.values()) == [1, 1]
            # skew observed on the poll loop: edge-triggered, exactly once
            wait_for(lambda: parse_prometheus(router.metrics_text()).get(
                obs_metrics.RECEIPT_SKEW, 0) >= 1, what="skew counter")
            router._check_fingerprint_skew()    # still skewed: no re-fire
            samples = parse_prometheus(router.metrics_text())
            assert samples[obs_metrics.RECEIPT_SKEW] == 1

            # pin tenant alpha to the good replica's fingerprint
            good_id = f"127.0.0.1:{good.port}"
            prompt = prompt_targeting(router, good_id)
            pinned = post_router(router, prompt, extra={"tenant": "alpha"})
            good_fp = _statusz(good.port)["readiness"]["fingerprint"]
            assert pinned["receipt"]["fingerprint"] == good_fp
            assert router.statusz()["tenants"]["pins"] == {"alpha": good_fp}

            # only the divergent replica remains: the pinned tenant sheds
            # typed-429 rather than landing on a config that would answer
            # differently
            hard_kill(good)
            wait_for(lambda: not any(
                r["ready"] and r["state"] == "healthy"
                and r["id"] == good_id
                for r in router.statusz()["replicas"]),
                what="good replica ejected")
            with pytest.raises(urllib.error.HTTPError) as err:
                post_router(router, prompt, extra={"tenant": "alpha"})
            assert err.value.code == 429
            assert err.value.headers.get("Retry-After")
            # an unpinned tenant still gets served by the divergent
            # replica — the shed is pin-scoped, not fleet-wide
            unpinned = post_router(router, prompt, extra={"tenant": "beta"})
            assert validate_receipt(unpinned["receipt"]) == []
            assert unpinned["receipt"]["fingerprint"] != good_fp
        finally:
            router.shutdown()
            bad.shutdown()


# ---------------------------------------------------------------------------
# golden-stream registry — units + the committed file
# ---------------------------------------------------------------------------

def _fake_matrix():
    return {
        "reference": "cellA",
        "perturb": None,
        "probes": {"digest": "abcd" * 4, "max_new_tokens": 12},
        "cells": {
            "cellA": {"status": "ref", "fingerprint": "f" * 16,
                      "tokens": [[1, 2, 3], [4, 5]]},
            "cellB": {"status": "agree", "fingerprint": "f" * 16,
                      "tokens": [[1, 2, 3], [4, 5]]},
            "cellS": {"status": "skipped", "reason": "unloadable here"},
        },
    }


class TestGoldenStreams:
    def test_doc_records_executed_cells_with_recomputable_digests(self):
        from reval_tpu.obs.determinism import golden_doc, validate_golden

        doc = golden_doc(_fake_matrix())
        assert set(doc["cells"]) == {"cellA", "cellB"}     # skipped stays out
        assert doc["cells"]["cellA"]["digests"] == [
            token_digest([1, 2, 3]), token_digest([4, 5])]
        assert validate_golden(doc) == []

    def test_validator_refuses_perturbed_and_tampered_registries(self):
        from reval_tpu.obs.determinism import golden_doc, validate_golden

        poisoned = _fake_matrix()
        poisoned["perturb"] = "cellA"
        assert any("PERTURB" in e for e in validate_golden(
            golden_doc(poisoned)))
        tampered = golden_doc(_fake_matrix())
        tampered["cells"]["cellA"]["tokens"][0][0] += 1
        assert any("recompute" in e for e in validate_golden(tampered))
        assert validate_golden({"schema": "wrong"})
        assert validate_golden("not a dict")

    def test_gate_names_cell_probe_and_token(self):
        from reval_tpu.obs.determinism import golden_doc, golden_gate

        golden = golden_doc(_fake_matrix())
        assert golden_gate(golden, _fake_matrix()) == []
        # a single flipped token: earliest-token attribution
        head = _fake_matrix()
        head["cells"]["cellB"]["tokens"] = [[1, 2, 3], [4, 9]]
        failures = golden_gate(golden, head)
        assert len(failures) == 1
        assert "cellB" in failures[0]
        assert "probe 1 token 1" in failures[0]
        # a recorded cell that stopped executing is loud, never silent
        gone = _fake_matrix()
        gone["cells"]["cellB"] = {"status": "skipped", "reason": "vanished"}
        assert any("did not execute" in m
                   for m in golden_gate(golden, gone))
        # probe-set change invalidates the whole comparison
        stale = _fake_matrix()
        stale["probes"]["digest"] = "ffff" * 4
        assert any("probe set changed" in m
                   for m in golden_gate(golden, stale))

    def test_committed_registry_validates_at_head(self):
        from reval_tpu.obs.determinism import (GOLDEN_FILE, GOLDEN_SLICE,
                                               validate_golden)

        path = os.path.join(REPO, GOLDEN_FILE)
        with open(path) as f:
            golden = json.load(f)
        assert validate_golden(golden) == []
        # the committed cells are a subset of the default slice (a
        # narrowed --record is allowed; unknown cells are not)
        assert set(golden["cells"]) <= set(GOLDEN_SLICE)

    def test_tool_record_then_perturbed_check_names_the_divergence(
            self, tmp_path, monkeypatch, capsys):
        """The full CLI gate on ONE host cell: ``--record`` blesses the
        stream, a perturbed HEAD (the determinism chaos hook) exits 1
        naming the cell and the first divergent (probe, token)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "golden_streams_under_test",
            os.path.join(REPO, "tools", "golden_streams.py"))
        tool = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tool)
        cell = "paged-xla-fp32-b2"
        path = str(tmp_path / "golden.json")
        assert tool.main(["--record", "--cells", cell,
                          "--path", path]) == 0
        monkeypatch.setenv("REVAL_TPU_DETERMINISM_PERTURB", cell)
        rc = tool.main(["--check", "--cells", cell, "--path", path])
        err = capsys.readouterr().err
        assert rc == 1
        assert "GOLDEN-STREAM GATE FAILURE" in err
        assert f"cell {cell}: token stream diverges from golden at " \
               "probe" in err
        assert "token" in err

    def test_goldenstreams_lint_pass_bites_on_corruption(self, tmp_path):
        from reval_tpu.analysis import goldenstreams
        from reval_tpu.obs.determinism import GOLDEN_FILE

        assert goldenstreams.run([], str(tmp_path)) == []   # no registry
        (tmp_path / GOLDEN_FILE).write_text("{ truncated")
        violations = goldenstreams.run([], str(tmp_path))
        assert violations and violations[0].pass_name == "goldenstreams"


# ---------------------------------------------------------------------------
# reporting surfaces — watch row, obs_report --receipts
# ---------------------------------------------------------------------------

class TestReceiptReporting:
    def test_watch_row_converged_skewed_and_single(self):
        from reval_tpu.watch import _receipt_row

        fp = "c0374e30" * 8
        converged = _receipt_row({"fingerprints": {fp: ["r1", "r2"]}})
        assert "converged" in converged and fp[:16] in converged
        skewed = _receipt_row({"fingerprints": {fp: ["r1", "r2"],
                                                "deadbeef" * 8: ["r3"]}})
        assert "SKEW" in skewed and "r3" in skewed and "r1" not in skewed
        single = _receipt_row({"readiness": {"fingerprint": fp,
                                             "engine_id": "e-1"}})
        assert fp[:16] in single and "e-1" in single
        assert _receipt_row({"readiness": {"ready": True}}) is None

    def test_obs_report_receipts_names_first_drift(self, tmp_path, capsys):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "obs_report_receipts", os.path.join(REPO, "tools",
                                                "obs_report.py"))
        tool = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tool)

        def round_file(name, fp, digest, perturb=None):
            path = tmp_path / name
            path.write_text(json.dumps(
                {"determinism": {"receipt_fingerprint": fp,
                                 "fingerprint": digest,
                                 "perturb": perturb}}))
            return str(path)

        rounds = [round_file("BENCH_r1.json", "aaaa", "d1"),
                  round_file("BENCH_r2.json", "aaaa", "d1"),
                  round_file("BENCH_r3.json", "zzzz", "d1", perturb="cell"),
                  round_file("BENCH_r4.json", "bbbb", "d2")]
        rc = tool.main(["--receipts"] + rounds)
        out = capsys.readouterr().out
        assert rc == 0
        # the perturbed round is marked and never the comparison bar:
        # the first REAL drift is r4 vs r2
        assert "[PERTURBED: cell]" in out
        assert "first drift: BENCH_r4.json" in out
        assert "BENCH_r2.json" in out.split("first drift", 1)[1]
        assert "fingerprint + digest DRIFTED" in out

    def test_obs_report_receipts_reads_fleet_trailers(self, tmp_path,
                                                      capsys):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "obs_report_receipts2", os.path.join(REPO, "tools",
                                                 "obs_report.py"))
        tool = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tool)
        skewed = tmp_path / "loadgen.json"
        skewed.write_text(json.dumps(
            {"receipts": {"fingerprints": ["aaaa", "bbbb"],
                          "converged": False}}))
        rc = tool.main(["--receipts", str(skewed)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "SKEW: 2 fleet fingerprints" in out
