"""Hierarchical KV page tiering (inference/tpu/kv_tiers.py).

Store units run jax-free: spill/promote round trips, chain-key
semantics, backpressure, the host-byte LRU bound, the on-disk page file
format, and every rung of the typed degrade ladder under seeded
``TierChaos``.  Engine tests pin the eval-harness contract on a tiny
CPU model: greedy token streams byte-identical across the resident,
spilled-and-promoted, and recomputed paths; the disk tier (snapshot v2
sidecar) promoting real bytes on a fresh engine; and the tier-1 chaos
drill — a diurnal multi-tenant loadgen workload over an
HBM-overflowing pool with corrupt+fail faults, zero lost prompts,
outputs byte-identical to the no-tier baseline.
"""

import os
import random
import sys

import numpy as np
import pytest

from reval_tpu.inference.tpu.engine import EngineStats
from reval_tpu.inference.tpu.kv_tiers import (
    TierEntry,
    TieredPageStore,
    TierIntegrityError,
    TierIOError,
    TierTimeoutError,
    _read_page_file,
    _write_page_file,
    chain_key,
)
from reval_tpu.obs import metrics as obs_metrics
from reval_tpu.obs.logging import recent
from reval_tpu.resilience import TierChaos

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))


def payload_for(seed: int, kb: int = 4) -> list[np.ndarray]:
    """A deterministic fake page payload (a few pool blocks)."""
    rng = np.random.default_rng(seed)
    n = (kb << 10) // 4 // 4
    return [rng.standard_normal(n).astype(np.float32) for _ in range(4)]


def make_store(**kw):
    kw.setdefault("host_mb", 64)
    kw.setdefault("queue_cap", 8)
    kw.setdefault("timeout_s", 5.0)
    return TieredPageStore(32, **kw)


def payloads_equal(a: list[np.ndarray], b: list[np.ndarray]) -> bool:
    return (len(a) == len(b)
            and all(x.tobytes() == y.tobytes() for x, y in zip(a, b)))


# ---------------------------------------------------------------------------
# Store units: spill → promote round trip, keys, backpressure, bounds
# ---------------------------------------------------------------------------

def test_spill_copier_promote_round_trip_bit_identical():
    stats = EngineStats()
    store = make_store(stats=lambda: stats)
    try:
        tokens = list(range(64))
        pay = payload_for(1)
        assert store.spill(tokens, pay) is True
        assert store.drain(5.0)
        entry = store.lookup(tokens)
        assert entry is not None and entry.tier == "host"
        assert payloads_equal(store.fetch(entry), pay)
        assert stats.kvtier_spills == 1
        got = store.counters()
        assert got["host_pages"] == 1
        assert got["host_bytes"] == sum(a.nbytes for a in pay)
        assert got["queue_depth"] == 0
    finally:
        store.close()


def test_chain_key_is_the_full_prefix_not_the_page():
    # identical page tokens under different prefixes must never alias:
    # KV rows encode attention over the ENTIRE root→page chain
    page = list(range(32, 64))
    assert chain_key([0] * 32 + page) != chain_key([1] * 32 + page)
    store = make_store(start_copier=False)
    try:
        store.put_host([0] * 32 + page, payload_for(2))
        assert store.lookup([1] * 32 + page) is None
        assert store.lookup([0] * 32 + page) is not None
    finally:
        store.close()


def test_spill_queue_backpressure_drops_never_blocks():
    stats = EngineStats()
    store = make_store(stats=lambda: stats, queue_cap=1,
                       start_copier=False)   # nobody drains the queue
    try:
        assert store.spill([1, 2], payload_for(3)) is True
        assert store.spill([3, 4], payload_for(4)) is False
        assert stats.kvtier_spill_drops == 1
        assert store.counters()["queue_depth"] == 1
    finally:
        store.close()


def test_duplicate_spill_is_refused():
    store = make_store(start_copier=False)
    try:
        store.put_host([7] * 32, payload_for(5))
        assert store.spill([7] * 32, payload_for(5)) is False
    finally:
        store.close()


def test_host_bound_lru_drops_bare_and_demotes_disk_backed(tmp_path):
    store = make_store(host_mb=1, start_copier=False)
    try:
        chains = [[i] * 32 for i in range(4)]
        for i, chain in enumerate(chains):
            store.put_host(chain, payload_for(i, kb=256))
        assert store.counters()["host_pages"] == 4      # exactly at bound
        # the oldest page now has a disk file: crossing the bound must
        # DEMOTE it (bytes live on disk), not lose it
        refs = store.write_disk(str(tmp_path / "pages"))
        assert len(refs) == 4
        store.put_host([9] * 32, payload_for(9, kb=256))
        got = store.counters()
        assert got["host_pages"] == 4
        demoted = store.lookup(chains[0])
        assert demoted is not None and demoted.tier == "disk"
        assert demoted.payload is None
        # the disk copy still serves the original bytes
        assert payloads_equal(store.fetch(demoted), payload_for(0, kb=256))
    finally:
        store.close()


def test_drop_adjusts_gauges_for_both_tiers(tmp_path):
    store = make_store(start_copier=False)
    try:
        store.put_host([1] * 32, payload_for(1))
        store.write_disk(str(tmp_path / "pages"))
        ref_store = make_store(start_copier=False)
        refs = [{"key": chain_key([1] * 32), "file": f"{chain_key([1]*32)}.kvpage",
                 "sha256": "0" * 64, "nbytes": 1}]
        assert ref_store.attach_disk(refs, str(tmp_path / "pages")) == 1
        assert ref_store.counters()["disk_pages"] == 1
        ref_store.drop(chain_key([1] * 32))
        assert ref_store.counters()["disk_pages"] == 0
        ref_store.close()
        store.drop(chain_key([1] * 32))
        got = store.counters()
        assert got["host_pages"] == 0 and got["host_bytes"] == 0
        store.drop("not-a-key")         # idempotent, never raises
    finally:
        store.close()


# ---------------------------------------------------------------------------
# The typed degrade ladder: every rung raises its own TierError
# ---------------------------------------------------------------------------

def test_integrity_rung_fires_on_tampered_payload():
    store = make_store(start_copier=False)
    try:
        entry = store.put_host([5] * 32, payload_for(6))
        entry.payload[0][0] += 1.0      # bit rot
        with pytest.raises(TierIntegrityError) as err:
            store.fetch(entry)
        assert err.value.reason == "integrity"
    finally:
        store.close()


def test_io_rung_fires_on_missing_disk_file_after_retry(tmp_path):
    store = make_store(start_copier=False)
    try:
        entry = TierEntry(key="k" * 64, checksum="0" * 64, nbytes=1,
                          payload=None,
                          path=str(tmp_path / "gone.kvpage"), tier="disk")
        with pytest.raises(TierIOError) as err:
            store.fetch(entry)
        assert err.value.reason == "io"
    finally:
        store.close()


@pytest.mark.parametrize("mode,exc", [
    ("fail", TierIOError),
    ("corrupt", TierIntegrityError),
    ("stall", TierTimeoutError),
])
def test_chaos_modes_map_to_typed_rungs(mode, exc):
    chaos = TierChaos(rate=1.0, seed=3, modes=(mode,), stall_s=0.05)
    store = make_store(start_copier=False, chaos=chaos,
                       timeout_s=0.01 if mode == "stall" else 5.0)
    try:
        entry = store.put_host([8] * 32, payload_for(8))
        with pytest.raises(exc):
            store.fetch(entry)
        assert chaos.injected and chaos.injected[0][0] == mode
        # chaos corrupts a COPY: the host payload itself stays good, so
        # dropping + re-spilling is recovery, not contagion
        if mode == "corrupt":
            assert payloads_equal(entry.payload, payload_for(8))
    finally:
        store.close()


def test_chaos_schedule_is_seeded_and_fault_bounded():
    a = TierChaos(rate=0.5, seed=11)
    b = TierChaos(rate=0.5, seed=11)
    keys = [chain_key([i] * 32) for i in range(40)]
    assert [a.draw(k) for k in keys] == [b.draw(k) for k in keys]
    assert any(m for m in (a.draw(k) for k in keys))    # some faults fired
    capped = TierChaos(rate=1.0, seed=0, max_faults=3)
    drawn = [capped.draw(k) for k in keys]
    assert sum(1 for m in drawn if m) == 3
    assert len(capped.injected) == 3


# ---------------------------------------------------------------------------
# The disk tier's on-disk shape: page files + snapshot refs
# ---------------------------------------------------------------------------

def test_page_file_round_trip_mixed_dtypes(tmp_path):
    path = str(tmp_path / "p.kvpage")
    pay = [np.arange(12, dtype=np.float32).reshape(3, 4),
           np.arange(8, dtype=np.int8)]
    _write_page_file(path, pay, "c" * 64)
    got = _read_page_file(path)
    assert payloads_equal(got, pay)
    assert [a.dtype for a in got] == [a.dtype for a in pay]
    assert [a.shape for a in got] == [a.shape for a in pay]


@pytest.mark.parametrize("mangle", ["magic", "header", "truncate"])
def test_page_file_corruption_raises_oserror(tmp_path, mangle):
    path = str(tmp_path / "p.kvpage")
    _write_page_file(path, payload_for(1), "c" * 64)
    raw = open(path, "rb").read()
    if mangle == "magic":
        raw = b"XXXX" + raw[4:]
    elif mangle == "header":
        raw = raw[:8] + b"{" * (len(raw) - 8)
    else:
        raw = raw[:-10]
    open(path, "wb").write(raw)
    with pytest.raises(OSError):
        _read_page_file(path)


def test_write_disk_attach_disk_round_trip_and_garbage_refs(tmp_path):
    side = str(tmp_path / "snap.pages")
    src = make_store(start_copier=False)
    chains = [[i] * 32 + [i + 1] * 32 for i in range(3)]
    for i, chain in enumerate(chains):
        src.put_host(chain, payload_for(i + 20))
    refs = src.write_disk(side)
    src.close()
    assert len(refs) == 3
    assert all(set(r) == {"key", "file", "sha256", "nbytes"} for r in refs)

    dst = make_store(start_copier=False)
    try:
        garbage = [None, 17, {"key": 1, "file": 2, "sha256": 3},
                   {"file": "x.kvpage", "sha256": "0" * 64}]
        assert dst.attach_disk(refs + garbage, side) == 3
        assert dst.counters()["disk_pages"] == 3
        for i, chain in enumerate(chains):
            entry = dst.lookup(chain)
            assert entry is not None and entry.tier == "disk"
            assert payloads_equal(dst.fetch(entry), payload_for(i + 20))
        # refs are idempotent: a second attach of the same keys is a no-op
        assert dst.attach_disk(refs, side) == 0
    finally:
        dst.close()


def test_close_is_idempotent_and_clears_everything():
    store = make_store()
    store.put_host([3] * 32, payload_for(3))
    store.close()
    store.close()
    assert store.counters() == {"host_pages": 0, "host_bytes": 0,
                                "disk_pages": 0, "queue_depth": 0}
    assert store.spill([4] * 32, payload_for(4)) is False   # stopped


# ---------------------------------------------------------------------------
# Engine contract: byte-identical across resident / promoted / recomputed
# ---------------------------------------------------------------------------

PAGE = 32                 # small pages so short prompts span FULL pages


@pytest.fixture(scope="module")
def tiny():
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer
    from reval_tpu.models import ModelConfig, init_random_params

    cfg = ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,  # 320
                      hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      head_dim=128)
    params = init_random_params(cfg, seed=0, dtype="float32")
    return cfg, params


def make_engine(tiny, **kw):
    from reval_tpu.inference.tpu.paged_engine import PagedTPUEngine
    from reval_tpu.inference.tpu.tokenizer import ByteTokenizer

    cfg, params = tiny
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_seq_len", 256)
    return PagedTPUEngine(params, cfg, ByteTokenizer(), **kw)


PROMPTS = [
    "def add(a, b):\n    return a + b\n# [QUESTION] is line 2 executed? ",
    "x = 1\nwhile x < 9:\n    x *= 2\n# [STATE] x = ",
    "y = [k * k for k in range(5)]\nassert y[3] == ",
]


def spill_all(eng) -> None:
    """Force every cached chain down to the host tier."""
    eng.prefix_cache.evict_lru(10 ** 6)
    assert eng.kv_tiers.drain(5.0)


def test_bit_identity_resident_promoted_recomputed(tiny):
    resident = make_engine(tiny, kv_tiering=False)
    want = resident.generate(PROMPTS, max_new_tokens=12, temperature=0.0)
    resident.close()

    eng = make_engine(tiny, kv_tiering=True)
    try:
        assert eng.generate(PROMPTS, max_new_tokens=12,
                            temperature=0.0) == want       # resident path
        spill_all(eng)
        promoted = eng.generate(PROMPTS, max_new_tokens=12, temperature=0.0)
        got = eng.kv_tier_counters()
        assert promoted == want
        assert got["promotions"] >= 1 and got["recomputes"] == 0

        # now every fetch fails: the SAME prompts must recompute from
        # their token chains and still produce the identical stream
        eng.kv_tiers.chaos = TierChaos(rate=1.0, seed=0, modes=("fail",))
        spill_all(eng)
        before = len(recent())
        recomputed = eng.generate(PROMPTS, max_new_tokens=12,
                                  temperature=0.0)
        got = eng.kv_tier_counters()
        assert recomputed == want
        assert got["recomputes"] >= 1
        degrades = [e for e in recent()[before:]
                    if e["event"] == "kvtier.degrade"]
        assert degrades and all(e["fields"]["reason"] == "io"
                                for e in degrades)
    finally:
        eng.close()


def test_corrupt_promotion_counts_integrity_and_stays_correct(tiny):
    eng = make_engine(tiny, kv_tiering=True)
    try:
        want = eng.generate(PROMPTS, max_new_tokens=8, temperature=0.0)
        eng.kv_tiers.chaos = TierChaos(rate=1.0, seed=1, modes=("corrupt",))
        spill_all(eng)
        before = len(recent())
        assert eng.generate(PROMPTS, max_new_tokens=8,
                            temperature=0.0) == want
        got = eng.kv_tier_counters()
        assert got["integrity_failures"] >= 1
        assert got["recomputes"] >= got["integrity_failures"]
        events = {e["event"] for e in recent()[before:]}
        assert "kvtier.integrity_failure" in events
        assert "kvtier.degrade" in events
    finally:
        eng.close()


def test_disk_tier_round_trip_promotes_real_bytes(tiny, tmp_path):
    side = str(tmp_path / "snap.pages")
    src = make_engine(tiny, kv_tiering=True)
    want = src.generate(PROMPTS, max_new_tokens=8, temperature=0.0)
    refs = src.dump_tier_pages(side)
    src.close()
    assert refs, "a drained engine with warm chains must dump page refs"

    dst = make_engine(tiny, kv_tiering=True)
    try:
        assert dst.attach_tier_refs(refs, side) == len(refs)
        got = dst.generate(PROMPTS, max_new_tokens=8, temperature=0.0)
        counters = dst.kv_tier_counters()
        assert got == want
        assert counters["disk_promotions"] >= 1
    finally:
        dst.close()


# ---------------------------------------------------------------------------
# The tier-1 chaos drill: diurnal multi-tenant load over an
# HBM-overflowing pool × corrupt+fail faults → zero lost prompts,
# outputs byte-identical to the no-tier baseline
# ---------------------------------------------------------------------------

def drill_workload():
    from loadgen import build_workload, diurnal_arrivals, synthetic_tenants

    arrivals = diurnal_arrivals(6.0, 30.0, 1.6, random.Random(16))
    tenants = synthetic_tenants({"alpha": 3, "beta": 1},
                                template_chars=96, max_tokens=8)
    return build_workload(arrivals, tenants, random.Random(16))


def run_drill(eng, reqs) -> list[str]:
    # the diurnal schedule fixes arrival ORDER; the trough between the
    # two peak waves reclaims the whole HBM pool (eviction pressure at
    # scale), so with tiering on every tenant template spills to host
    # DRAM and the second peak promotes it back — under chaos faults
    half = len(reqs) // 2
    outs = eng.generate([r.prompt for r in reqs[:half]],
                        max_new_tokens=8, temperature=0.0)
    eng.prefix_cache.evict_lru(10 ** 6)
    if eng.kv_tiers is not None:
        assert eng.kv_tiers.drain(5.0)
    outs.extend(eng.generate([r.prompt for r in reqs[half:]],
                             max_new_tokens=8, temperature=0.0))
    return outs


def test_kvtier_chaos_drill_zero_lost_byte_identical(tiny):
    reqs = drill_workload()
    assert len(reqs) >= 12, "diurnal schedule too thin for a drill"

    baseline = make_engine(tiny, kv_tiering=False, num_pages=28)
    want = run_drill(baseline, reqs)
    assert baseline.stats.prefix_evictions >= 1, \
        "pool must overflow HBM for the drill to mean anything"
    baseline.close()

    chaos = TierChaos(rate=0.5, seed=16, modes=("corrupt", "fail"))
    eng = make_engine(tiny, kv_tiering=True, num_pages=28,
                      tier_chaos=chaos)
    try:
        got = run_drill(eng, reqs)
        counters = eng.kv_tier_counters()
    finally:
        eng.close()

    assert len(got) == len(reqs)                    # zero lost prompts
    assert got == want                              # byte-identical logs
    assert counters["spills"] >= 1
    assert counters["recomputes"] >= 1              # faults really landed
    assert chaos.injected, "chaos schedule never fired — drill is vacuous"


# ---------------------------------------------------------------------------
# Surfaces: watch row, loadgen artifact block
# ---------------------------------------------------------------------------

def test_watch_kvtier_row_renders_and_hides_when_idle():
    from reval_tpu.watch import _kvtier_row

    assert _kvtier_row({}, {}) is None
    counters = {obs_metrics.KVTIER_SPILLS: 4,
                obs_metrics.KVTIER_PROMOTIONS: 3,
                obs_metrics.KVTIER_RECOMPUTES: 1,
                obs_metrics.KVTIER_INTEGRITY_FAILURES: 1}
    gauges = {obs_metrics.KVTIER_HOST_PAGES: 5,
              obs_metrics.KVTIER_DISK_PAGES: 2,
              obs_metrics.KVTIER_QUEUE_DEPTH: 1}
    row = _kvtier_row(counters, gauges)
    assert "host 5p" in row and "disk 2p" in row and "queue 1" in row
    assert "spills 4" in row and "promotions 3" in row
    assert "recomputes 1" in row and "integrity_fail 1" in row


def test_loadgen_kvtier_block_deltas_and_hit_rate():
    from loadgen import OpenLoopRunner

    before = {obs_metrics.KVTIER_SPILLS: 10.0,
              obs_metrics.KVTIER_PROMOTIONS: 6.0,
              obs_metrics.KVTIER_RECOMPUTES: 2.0}
    after = {obs_metrics.KVTIER_SPILLS: 16.0,
             obs_metrics.KVTIER_PROMOTIONS: 12.0,
             obs_metrics.KVTIER_RECOMPUTES: 4.0,
             obs_metrics.KVTIER_INTEGRITY_FAILURES: 1.0}
    block = OpenLoopRunner._kvtier_block(before, after)
    assert block["spills"] == 6 and block["promotions"] == 6
    assert block["recomputes"] == 2 and block["integrity_failures"] == 1
    assert block["promote_hit_rate"] == 0.75
    # None when the target has no tier traffic (mock fleet) or no scrape
    assert OpenLoopRunner._kvtier_block(None, None) is None
    assert OpenLoopRunner._kvtier_block(before, dict(before)) is None
