"""End-to-end task-engine tests: full plan→infer→score runs, model-free
(SURVEY §7 step 3: 'the whole framework runs GPU-free via replay/mock')."""

import json

import pytest

from reval_tpu.dynamics import Nil
from reval_tpu.inference import MockBackend, ReplayBackend, ScriptedBackend
from reval_tpu.tasks import (
    TASKS,
    ConsistencyScorer,
    CoverageTask,
    OutputTask,
    PathTask,
    ResultsStore,
    StateTask,
)

N_ITEMS = 3  # benchmark rows per smoke run


def oracle_responses(task_name: str, jobs) -> list[str]:
    """Craft correct answers from the planner's precomputed ground truth."""
    responses = []
    for job in jobs:
        if task_name == "coverage":
            responses.append("YES" if job.expected else "NO")
        elif task_name == "path":
            succ = job.expected[0]
            if succ == -1:
                responses.append("-1")
            else:
                responses.append(job.context["codelines"][succ - 1].strip())
        elif task_name == "state":
            if job.expected is Nil:
                responses.append("Nil")
            else:
                v = job.expected[0]
                responses.append(f"{v!r}; {type(v).__name__}")
        elif task_name == "output":
            _input = job.context["_input"]
            call = _input[len("assert"):_input.rfind("==")].strip()
            value = job.context["space"].eval_invocation(call)
            responses.append(_input.replace("??", repr(value)))
    return responses


def run_with_oracle(task_cls, tmp_path, dataset="humaneval"):
    planner = task_cls(model=None, prompt_type="direct", dataset=dataset,
                       mock=True, results_dir=str(tmp_path), max_items=N_ITEMS, progress=False)
    _, jobs = planner._plan()
    responses = oracle_responses(task_cls.name, jobs)
    backend = ScriptedBackend(responses, model_id="oracle")
    task = task_cls(model=backend, prompt_type="direct", dataset=dataset,
                    results_dir=str(tmp_path), max_items=N_ITEMS, progress=False)
    return task.run(), task


class TestCoverageE2E:
    def test_all_yes_backend(self, tmp_path):
        backend = ScriptedBackend(["YES"] * 500, model_id="allyes")
        task = CoverageTask(model=backend, prompt_type="direct", dataset="humaneval",
                            results_dir=str(tmp_path), max_items=N_ITEMS, progress=False)
        metrics = task.run()
        assert metrics["total"] > 0
        assert set(metrics) == {"total", "acc", "prec", "rec", "f1"}
        # all-YES: recall is 1, accuracy = positive rate
        assert metrics["rec"] == 1.0
        assert 0 < metrics["acc"] <= 1.0

    def test_oracle_scores_100(self, tmp_path):
        metrics, task = run_with_oracle(CoverageTask, tmp_path)
        assert metrics["acc"] == 1.0
        assert metrics["f1"] == 1.0
        # results file on disk, metrics trailer included
        rows = ResultsStore.read(task.store.latest("humaneval"))
        assert rows[-1] == metrics
        assert rows[0]["task_id"].startswith("DREval/")
        assert {"generated", "response", "expected"} <= set(rows[0]["generation"][0]["results"][0])


class TestPathE2E:
    def test_oracle_scores_100(self, tmp_path):
        metrics, task = run_with_oracle(PathTask, tmp_path)
        assert metrics["acc"] == 1.0
        rows = ResultsStore.read(task.store.latest("humaneval"))
        rec = rows[0]["generation"][0]["results"][0]
        # single enriched record per probe (reference's double-append fixed)
        assert {"generated", "response", "expected", "line", "prompt", "result"} <= set(rec)

    def test_numbered_code_in_prompt(self, tmp_path):
        planner = PathTask(model=None, prompt_type="direct", dataset="humaneval",
                           mock=True, results_dir=str(tmp_path), max_items=1, progress=False)
        _, jobs = planner._plan()
        assert "1\t" in jobs[0].prompt  # line-number prefixes present

    def test_classeval_code_not_numbered(self, tmp_path):
        # reference evaluation.py:574-582: ClassEval path prompts are raw code
        planner = PathTask(model=None, prompt_type="direct", dataset="classeval",
                           mock=True, results_dir=str(tmp_path), max_items=1, progress=False)
        _, jobs = planner._plan()
        assert jobs and "1\timport" not in jobs[0].prompt
        assert "2\t" not in jobs[0].prompt


class TestStateE2E:
    def test_oracle_scores_high(self, tmp_path):
        # repr-roundtrip oracle can't express exotic values; accept >= 0.8
        metrics, task = run_with_oracle(StateTask, tmp_path)
        assert metrics["total"] > 0
        assert metrics["acc"] >= 0.8
        rows = ResultsStore.read(task.store.latest("humaneval"))
        rec = rows[0]["generation"][0]["results"][0]
        assert {"generated", "eq", "line", "var"} <= set(rec)
        json.dumps(rows)  # every record must be JSON-clean

    def test_classeval_flow(self, tmp_path):
        backend = ScriptedBackend(["Nil"] * 200, model_id="nil")
        task = StateTask(model=backend, prompt_type="direct", dataset="classeval",
                         results_dir=str(tmp_path), max_items=2, progress=False)
        metrics = task.run()
        assert metrics["total"] > 0


class TestOutputE2E:
    def test_oracle_passes(self, tmp_path):
        metrics, task = run_with_oracle(OutputTask, tmp_path)
        assert metrics["acc"] == 1.0

    def test_wrong_answers_fail(self, tmp_path):
        backend = ScriptedBackend(["assert 1 == 2"] * 50, model_id="wrong")
        task = OutputTask(model=backend, prompt_type="direct", dataset="humaneval",
                          results_dir=str(tmp_path), max_items=N_ITEMS, progress=False)
        metrics = task.run()
        assert metrics["acc"] == 0.0

    def test_penalty_blocks_trivial(self, tmp_path):
        backend = ScriptedBackend(["assert True"] * 50, model_id="cheat")
        task = OutputTask(model=backend, prompt_type="direct", dataset="humaneval",
                          results_dir=str(tmp_path), max_items=N_ITEMS, progress=False)
        metrics = task.run()
        assert metrics["acc"] == 0.0


class TestConsistencyE2E:
    def test_oracle_ladder(self, tmp_path):
        infos = set()
        for task_cls in (CoverageTask, StateTask, PathTask, OutputTask):
            _, task = run_with_oracle(task_cls, tmp_path)
            infos.add(task.store.model_info)
        assert infos == {"oracle_direct_temp0.8"}
        scorer = ConsistencyScorer("oracle_direct_temp0.8", "humaneval",
                                   results_dir=str(tmp_path), progress=False)
        score = scorer.run()
        # coverage+path+output oracles are perfect; state ≥0.8 → score ≥ 50
        assert score >= 50.0


class TestConsistencyLadder:
    @staticmethod
    def _score_one(c: bool, s: bool, p: bool, o: bool) -> float:
        """Run the real scorer on a single aligned test case."""
        from reval_tpu.tasks.consistency import ConsistencyScorer

        scorer = object.__new__(ConsistencyScorer)
        scorer.progress = False
        trailer = {"acc": 0.0}

        def rows(atomic, n_results=1):
            return [{"generation": [{"results": [atomic] * n_results}]}, trailer]

        scorer.logs = {
            "coverage": rows({"response": True, "expected": c}),
            "state": rows({"eq": s}),
            "path": rows({"response": [3], "expected": [3] if p else [7]}),
            "output": rows({"pass": o}),
        }
        return scorer.run()

    def test_reference_ladder_table(self):
        # the reference-defined table (evaluation.py:1055-1062), via run()
        assert self._score_one(True, True, True, True) == 100.0
        assert self._score_one(True, True, True, False) == 50.0
        assert self._score_one(True, True, False, False) == 25.0
        assert self._score_one(True, False, False, False) == 12.5
        # non-monotone patterns earn nothing (exclusive rungs)
        assert self._score_one(True, True, False, True) == 0.0
        assert self._score_one(True, False, True, True) == 0.0
        assert self._score_one(False, True, True, True) == 0.0


class TestModelInfo:
    def test_matches_backend_naming(self):
        from reval_tpu.inference.base import model_info_from_config

        assert model_info_from_config({"custom_mock": True, "prompt_type": "cot"}) == "mock_model_cot"
        assert model_info_from_config(
            {"model_id": "gpt-3.5", "prompt_type": "direct", "temp": 0.8}
        ) == "gpt-3.5-turbo-0125_direct_temp0.8"
        # int temps normalise like the backend's float cast
        assert model_info_from_config(
            {"model_id": "m", "prompt_type": "direct", "temp": 1}
        ) == "m_direct_temp1.0"


class TestReplayE2E:
    def test_replay_reproduces_metrics(self, tmp_path):
        metrics1, task1 = run_with_oracle(CoverageTask, tmp_path)
        backend = ReplayBackend(replay_task="coverage", model_id="oracle",
                                prompt_type="direct", results_dir=str(tmp_path))
        task2 = CoverageTask(model=backend, prompt_type="direct", dataset="humaneval",
                             results_dir=str(tmp_path), max_items=N_ITEMS, progress=False)
        metrics2 = task2.run()
        assert metrics1 == metrics2


class TestMockBackend:
    def test_mock_run_completes(self, tmp_path):
        backend = MockBackend()
        task = CoverageTask(model=backend, prompt_type="direct", dataset="humaneval",
                            custom_mock=True, results_dir=str(tmp_path),
                            max_items=2, progress=False)
        metrics = task.run()
        assert metrics["total"] > 0
        assert task.store.model_info == "mock_model_direct"


class TestMbppMathqa:
    def test_mbpp_coverage_smoke(self, tmp_path):
        backend = ScriptedBackend(["YES"] * 200, model_id="y")
        task = CoverageTask(model=backend, prompt_type="direct", dataset="mbpp",
                            results_dir=str(tmp_path), max_items=2, progress=False)
        metrics = task.run()
        assert metrics["total"] > 0

    def test_mathqa_state_smoke(self, tmp_path):
        backend = ScriptedBackend(["0.0; float"] * 200, model_id="f")
        task = StateTask(model=backend, prompt_type="direct", dataset="mathqa",
                         results_dir=str(tmp_path), max_items=2, progress=False)
        metrics = task.run()
        assert metrics["total"] > 0


def test_output_reference_compat_prompts():
    """reference_compat restores the reference's MBPP output prompts (bare
    invocation, no ??-assert) for strict accuracy comparability."""
    from reval_tpu.tasks import OutputTask

    def question(prompt):                         # text after the few-shot
        return prompt.rsplit("[PYTHON]", 1)[1]

    ours = OutputTask(prompt_type="direct", dataset="mbpp", mock=True,
                      max_items=1, progress=False)
    _, jobs = ours._plan()
    assert "??" in question(jobs[0].prompt)       # default: the real question

    compat = OutputTask(prompt_type="direct", dataset="mbpp", mock=True,
                        max_items=1, progress=False, reference_compat=True)
    _, cjobs = compat._plan()
    assert "??" not in question(cjobs[0].prompt)  # reference: bare invocation
    assert len(cjobs) == len(jobs)
