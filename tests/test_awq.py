"""AWQ pre-quantized checkpoint ingestion (models/awq.py + loader).

The reference loads published 4-bit checkpoints through vLLM's AWQ
support (reference inference.py:93).  No egress here, so a synthetic
writer produces a bit-faithful AWQ-GEMM checkpoint (packing order
AWQ_ORDER, asymmetric zero points, fp16 group scales) and the loader
must reproduce ``(q - z) * s`` exactly through the int4 + gscale +
gzero storage."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from reval_tpu.models.awq import AWQ_ORDER, awq_to_leaves, pack_awq, unpack_awq

GROUP = 64


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(0)
    vals = rng.randint(0, 16, size=(32, 24)).astype(np.uint8)
    packed = pack_awq(vals)
    assert packed.shape == (32, 3) and packed.dtype == np.int32
    np.testing.assert_array_equal(unpack_awq(packed), vals)


def test_order_map_is_awq_gemm():
    # one block of 8 columns with value == logical column index: nibble p
    # must hold column AWQ_ORDER[p]
    vals = np.arange(8, dtype=np.uint8)[None, :]
    packed = pack_awq(vals).astype(np.uint32)[0, 0]
    for p, col in enumerate(AWQ_ORDER):
        assert (packed >> (4 * p)) & 0xF == col


def test_awq_to_leaves_reproduces_dequant_formula():
    rng = np.random.RandomState(1)
    n_in, n_out = 128, 32
    q = rng.randint(0, 16, size=(n_in, n_out)).astype(np.uint8)
    z = rng.randint(0, 16, size=(n_in // GROUP, n_out)).astype(np.uint8)
    s = (rng.rand(n_in // GROUP, n_out).astype(np.float16) * 0.1)

    w, gscale, gzero = awq_to_leaves(pack_awq(q), pack_awq(z), s)
    from reval_tpu.models.quant import dequantize_grouped

    got = np.asarray(dequantize_grouped(
        jnp.asarray(w), jnp.asarray(gscale), jnp.float32, jnp.asarray(gzero)))
    want = ((q.astype(np.float32) - np.repeat(z, GROUP, 0))
            * np.repeat(s.astype(np.float32), GROUP, 0))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def _quantize_awq(w_out_in: np.ndarray, group: int):
    """Reference asymmetric int4 group quantizer producing AWQ tensors
    for one linear (HF weight [out, in] -> AWQ [in, out] layout)."""
    w = w_out_in.T.astype(np.float32)              # [in, out]
    n_in, n_out = w.shape
    wg = w.reshape(n_in // group, group, n_out)
    lo, hi = wg.min(axis=1), wg.max(axis=1)        # [G, out]
    s = np.maximum((hi - lo) / 15.0, 1e-8)
    z = np.clip(np.round(-lo / s), 0, 15)
    q = np.clip(np.round(wg / s[:, None, :]) + z[:, None, :], 0, 15)
    return (pack_awq(q.reshape(n_in, n_out).astype(np.uint8)),
            pack_awq(z.astype(np.uint8)), s.astype(np.float16))


@pytest.fixture(scope="module")
def awq_checkpoint(tmp_path_factory):
    """Tiny llama checkpoint in genuine AWQ-GEMM on-disk format."""
    import torch
    from safetensors.numpy import save_file
    from transformers import LlamaConfig, LlamaForCausalLM

    path = tmp_path_factory.mktemp("ckpt") / "tiny-llama-awq"
    path.mkdir()
    torch.manual_seed(3)
    hf_cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=4, tie_word_embeddings=False)
    model = LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.float().numpy() for k, v in model.state_dict().items()}

    tensors: dict = {}
    for name, arr in sd.items():
        if (name.endswith(".weight") and arr.ndim == 2
                and "embed_tokens" not in name and "norm" not in name):
            base = name.removesuffix(".weight")
            qw, qz, sc = _quantize_awq(arr, GROUP)
            tensors[base + ".qweight"] = qw
            tensors[base + ".qzeros"] = qz
            tensors[base + ".scales"] = sc
        else:
            tensors[name] = arr.astype(np.float32)
    save_file(tensors, str(path / "model.safetensors"))

    cfg = json.loads(hf_cfg.to_json_string())
    cfg["quantization_config"] = {"quant_method": "awq", "bits": 4,
                                  "group_size": GROUP, "zero_point": True,
                                  "version": "gemm"}
    (path / "config.json").write_text(json.dumps(cfg))
    return model, path


@pytest.mark.slow
def test_awq_checkpoint_loads_and_matches_dequant(awq_checkpoint):
    """Loaded AWQ leaves dequantise to exactly the values the AWQ formula
    assigns, and greedy generation matches an engine fed those values."""
    from reval_tpu.inference.tpu.engine import TPUEngine
    from reval_tpu.models import load_checkpoint
    from reval_tpu.models.quant import dequantize_params, is_quantized

    model, path = awq_checkpoint
    params, cfg = load_checkpoint(path, dtype="float32")
    assert is_quantized(params)
    assert params["layers"]["q_w"].dtype == jnp.int4
    assert "q_w_gzero" in params["layers"]
    assert "lm_head_gzero" in params           # untied, quantized head

    # leaf-level exactness vs the on-disk AWQ dequant formula
    qw = np.asarray(model.state_dict()["model.layers.0.self_attn.q_proj.weight"],
                    np.float32)
    pk, zk, sk = _quantize_awq(qw, GROUP)
    from reval_tpu.models.awq import awq_to_leaves

    w0, s0, z0 = awq_to_leaves(pk, zk, sk)
    deq = dequantize_params(params)
    want0 = ((unpack_awq(pk).astype(np.float32)
              - np.repeat(unpack_awq(zk), GROUP, 0))
             * np.repeat(sk.astype(np.float32), GROUP, 0))
    np.testing.assert_allclose(np.asarray(deq["layers"]["q_w"][0]), want0,
                               rtol=1e-5, atol=1e-6)

    class _Tok:
        eos_id, pad_id = 127, 0

        def encode(self, text):
            return [ord(c) % 120 + 1 for c in text]

        def decode(self, ids):
            return "".join(chr(32 + (int(i) % 90)) for i in ids)

    prompts = ["def f(x):", "x = 1"]
    eng = TPUEngine(params, cfg, _Tok(), batch_size=2, max_seq_len=256)
    got = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    oracle = TPUEngine(deq, cfg, _Tok(), batch_size=2, max_seq_len=256)
    assert got == oracle.generate(prompts, max_new_tokens=8, temperature=0.0)


def test_awq_detection_rejects_unsupported_bits(tmp_path):
    from reval_tpu.models.awq import awq_config

    (tmp_path / "config.json").write_text(json.dumps(
        {"quantization_config": {"quant_method": "awq", "bits": 8}}))
    with pytest.raises(ValueError, match="bits"):
        awq_config(tmp_path)
    (tmp_path / "config.json").write_text(json.dumps({"model_type": "llama"}))
    assert awq_config(tmp_path) is None


@pytest.mark.slow
def test_awq_loads_through_sharded_loader_fallback(awq_checkpoint):
    """Engines route mesh loads through load_checkpoint_sharded; an AWQ
    checkpoint must come back complete and sharded (full-tree fallback),
    not silently missing every projection."""
    import jax

    from reval_tpu.models import load_checkpoint_sharded
    from reval_tpu.models.quant import is_quantized
    from reval_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    _, path = awq_checkpoint
    params, cfg = load_checkpoint_sharded(path, make_mesh(tp=2),
                                          dtype="float32")
    assert is_quantized(params)
    assert params["layers"]["q_w"].dtype == jnp.int4
    assert "q_w_gzero" in params["layers"]
    assert not cfg.tie_word_embeddings


def test_gemv_version_rejected(tmp_path):
    from reval_tpu.models.awq import awq_config

    (tmp_path / "config.json").write_text(json.dumps(
        {"quantization_config": {"quant_method": "awq", "bits": 4,
                                 "version": "gemv"}}))
    with pytest.raises(ValueError, match="GEMM"):
        awq_config(tmp_path)


def test_requantizing_quantized_tree_refused(awq_checkpoint):
    from reval_tpu.models import load_checkpoint
    from reval_tpu.models.quant import quantize_params

    _, path = awq_checkpoint
    params, _ = load_checkpoint(path, dtype="float32")
    with pytest.raises(ValueError, match="already quantized"):
        quantize_params(params, mode="int4")


# -- GPTQ ------------------------------------------------------------------

def _quantize_gptq(w_out_in: np.ndarray, group: int):
    """Reference GPTQ writer: row-packed qweight, col-packed qzeros
    stored z-1 (AutoGPTQ v1 semantics)."""
    from reval_tpu.models.awq import pack_gptq_cols, pack_gptq_rows

    w = w_out_in.T.astype(np.float32)              # [in, out]
    n_in, n_out = w.shape
    wg = w.reshape(n_in // group, group, n_out)
    lo, hi = wg.min(axis=1), wg.max(axis=1)
    s = np.maximum((hi - lo) / 15.0, 1e-8)
    z = np.clip(np.round(-lo / s), 1, 15)          # >=1 so stored z-1 >= 0
    q = np.clip(np.round(wg / s[:, None, :]) + z[:, None, :], 0, 15)
    return (pack_gptq_rows(q.reshape(n_in, n_out).astype(np.uint8)),
            pack_gptq_cols((z - 1).astype(np.uint8)), s.astype(np.float16))


def test_gptq_pack_unpack_roundtrip():
    from reval_tpu.models.awq import (pack_gptq_cols, pack_gptq_rows,
                                      unpack_gptq_cols, unpack_gptq_rows)

    rng = np.random.RandomState(4)
    vals = rng.randint(0, 16, size=(64, 24)).astype(np.uint8)
    np.testing.assert_array_equal(unpack_gptq_rows(pack_gptq_rows(vals)), vals)
    np.testing.assert_array_equal(unpack_gptq_cols(pack_gptq_cols(vals)), vals)


def test_gptq_to_leaves_reproduces_dequant_formula():
    from reval_tpu.models.awq import gptq_to_leaves
    from reval_tpu.models.quant import dequantize_grouped

    rng = np.random.RandomState(5)
    n_in, n_out = 128, 32
    w_hf = rng.randn(n_out, n_in).astype(np.float32) * 0.05   # HF [out, in]
    qw, qz, sc = _quantize_gptq(w_hf, GROUP)
    w, gs, gz = gptq_to_leaves(qw, qz, sc)
    got = np.asarray(dequantize_grouped(
        jnp.asarray(w), jnp.asarray(gs), jnp.float32, jnp.asarray(gz)))
    # oracle: (q - (z_stored + 1)) * s with true unpacked values
    from reval_tpu.models.awq import unpack_gptq_cols, unpack_gptq_rows

    q = unpack_gptq_rows(qw).astype(np.float32)
    z = unpack_gptq_cols(qz).astype(np.float32) + 1.0
    want = (q - np.repeat(z, GROUP, 0)) * np.repeat(
        sc.astype(np.float32), GROUP, 0)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.fixture(scope="module")
def gptq_checkpoint(tmp_path_factory):
    import torch
    from safetensors.numpy import save_file
    from transformers import LlamaConfig, LlamaForCausalLM

    path = tmp_path_factory.mktemp("ckpt") / "tiny-llama-gptq"
    path.mkdir()
    torch.manual_seed(6)
    hf_cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=4, tie_word_embeddings=False)
    model = LlamaForCausalLM(hf_cfg).eval()
    tensors: dict = {}
    for name, arr in ((k, v.float().numpy())
                      for k, v in model.state_dict().items()):
        if (name.endswith(".weight") and arr.ndim == 2
                and "embed_tokens" not in name and "norm" not in name):
            base = name.removesuffix(".weight")
            qw, qz, sc = _quantize_gptq(arr, GROUP)
            tensors[base + ".qweight"] = qw
            tensors[base + ".qzeros"] = qz
            tensors[base + ".scales"] = sc
        else:
            tensors[name] = arr.astype(np.float32)
    save_file(tensors, str(path / "model.safetensors"))
    cfg = json.loads(hf_cfg.to_json_string())
    cfg["quantization_config"] = {"quant_method": "gptq", "bits": 4,
                                  "group_size": GROUP, "desc_act": False}
    (path / "config.json").write_text(json.dumps(cfg))
    return path


@pytest.mark.slow
def test_gptq_checkpoint_loads_and_matches_oracle(gptq_checkpoint):
    from reval_tpu.inference.tpu.engine import TPUEngine
    from reval_tpu.models import load_checkpoint
    from reval_tpu.models.quant import dequantize_params, is_quantized

    params, cfg = load_checkpoint(gptq_checkpoint, dtype="float32")
    assert is_quantized(params)
    assert params["layers"]["q_w"].dtype == jnp.int4
    assert "q_w_gzero" in params["layers"]

    class _Tok:
        eos_id, pad_id = 127, 0

        def encode(self, text):
            return [ord(c) % 120 + 1 for c in text]

        def decode(self, ids):
            return "".join(chr(32 + (int(i) % 90)) for i in ids)

    prompts = ["def f(x):", "x = 1"]
    eng = TPUEngine(params, cfg, _Tok(), batch_size=2, max_seq_len=256)
    oracle = TPUEngine(dequantize_params(params), cfg, _Tok(), batch_size=2,
                       max_seq_len=256)
    assert (eng.generate(prompts, max_new_tokens=8, temperature=0.0)
            == oracle.generate(prompts, max_new_tokens=8, temperature=0.0))


def test_gptq_desc_act_rejected(tmp_path):
    from reval_tpu.models.awq import gptq_config

    (tmp_path / "config.json").write_text(json.dumps(
        {"quantization_config": {"quant_method": "gptq", "bits": 4,
                                 "desc_act": True}}))
    with pytest.raises(ValueError, match="desc_act"):
        gptq_config(tmp_path)


def test_gptq_v2_format_rejected(tmp_path):
    from reval_tpu.models.awq import gptq_config

    (tmp_path / "config.json").write_text(json.dumps(
        {"quantization_config": {"quant_method": "gptq", "bits": 4,
                                 "desc_act": False,
                                 "checkpoint_format": "gptq_v2"}}))
    with pytest.raises(ValueError, match="checkpoint_format"):
        gptq_config(tmp_path)
